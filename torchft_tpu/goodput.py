"""Fleet goodput ledger: per-second badput attribution over the trace ring.

The observability planes before this one answer *what happened* (tracing's
causal timeline, metrics' cumulative counters, telemetry's event records).
None answers the question a production fleet is judged by: what fraction of
paid wall-clock became committed training progress, and which subsystem ate
the rest? This module adds that currency:

- :func:`fold_events` — a conservation-exact fold over trace-ring events
  that partitions a ``[t0, t1]`` monotonic window into exactly one of the
  :data:`BUCKETS` per elementary segment, so the buckets sum to the
  wall-clock width by construction. It is a *fold over the existing ring*
  (tracing.py already tags every FT phase), never new hot-path
  instrumentation; the per-event cost is pinned <= 5 us by a unit test.
- :class:`GoodputLedger` — closes windows on the metrics-push cadence,
  retains them in a byte-budgeted :class:`metrics.WindowedSeries` ring so
  rates are queryable live, counts ``tpuft_goodput_*``, and builds the
  ``goodput`` payload each Manager pushes through the quorum store
  (feeding fleet_status's GOODPUT column, ``scripts/goodput_report.py``,
  and the bench line's ``goodput_fraction``).
- :class:`SloEvaluator` — declarative burn-rate alerting
  (``TPUFT_SLO_GOODPUT=0.95`` style) with the health plane's K-consecutive
  -windows hysteresis: a window "burns" when badput spends the error
  budget faster than ``TPUFT_SLO_BURN_RATE``; K consecutive burning
  windows latch exactly ONE breach (telemetry record on the ``tpuft_slo``
  logger + ``slo_breach`` trace event + incident auto-dump), re-armed
  only by a healthy window. Alerting, never actuation — the health plane
  (health.py) owns ejection; this plane only pages.
- :func:`merge_windows` — merges per-replica pushed payloads into one
  fleet goodput number + per-cause and per-region badput breakdowns.

Attribution model: mapped trace SPANS claim their interval (overlaps
resolve by fixed priority — a heal stripe inside a quorum wait is heal
time), and the time *between* spans is ambient: attributed to the next
outcome instant at-or-after the segment (``commit`` -> committed compute;
``commit_failed``/``rollback``/``speculation_discarded`` -> rollback
recompute), or ``idle`` when no outcome follows in the window — so a dead
replica's post-death window honestly reads idle, and device dispatch /
wire time leading into a commit counts as the committed compute it was.
A joiner's ``heal_recv`` start additionally fences the lookahead
(:data:`BOUNDARY_SPANS`): dead time before a heal reads idle even when
the healed replica commits later in the same window.

Docs: docs/observability.md section 0; METRICS.md rows; reference framing
per PAPERS.md availability accounting (goodput, not step counts).
"""

from __future__ import annotations

import math
import os
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from torchft_tpu import metrics, telemetry, tracing

__all__ = [
    "BUCKETS",
    "SPAN_BUCKETS",
    "OUTCOME_BUCKETS",
    "BOUNDARY_SPANS",
    "ENV_WINDOW_SEC",
    "ENV_WINDOWS",
    "ENV_BYTES",
    "ENV_SLO_GOODPUT",
    "ENV_SLO_WINDOWS",
    "ENV_SLO_BURN_RATE",
    "fold_events",
    "top_badput",
    "GoodputLedger",
    "SloEvaluator",
    "merge_windows",
]

ENV_WINDOW_SEC = "TPUFT_GOODPUT_WINDOW_SEC"
ENV_WINDOWS = "TPUFT_GOODPUT_WINDOWS"
ENV_BYTES = "TPUFT_GOODPUT_BYTES"
ENV_SLO_GOODPUT = "TPUFT_SLO_GOODPUT"
ENV_SLO_WINDOWS = "TPUFT_SLO_WINDOWS"
ENV_SLO_BURN_RATE = "TPUFT_SLO_BURN_RATE"

# Every second of every replica's wall-clock lands in exactly one of these.
BUCKETS: Tuple[str, ...] = (
    "committed_compute",
    "commit_wait",
    "quorum_wait",
    "drain",
    "heal_donor",
    "heal_joiner",
    "rollback_recompute",
    "degraded",
    "idle",
)

# Span name -> bucket, priority-ordered (first listed wins an overlap): a
# heal stripe served while parked in a quorum wait is heal time, a drain
# inside a quorum round is drain time. Spans NOT listed here (device_sync,
# update_dispatch, wire_bucket, ...) stay ambient on purpose — dispatch and
# wire time leading into a commit IS the committed compute being paid for.
SPAN_BUCKETS: Tuple[Tuple[str, str], ...] = (
    ("heal_recv", "heal_joiner"),
    ("heal_send", "heal_donor"),
    ("pipeline_drain", "drain"),
    ("zero_rebalance", "drain"),
    ("health_quarantine", "degraded"),
    ("quorum", "quorum_wait"),
    ("pg_configure", "quorum_wait"),
    ("commit_barrier", "commit_wait"),
)

# Outcome instants that classify the ambient time leading up to them.
OUTCOME_BUCKETS: Dict[str, str] = {
    "commit": "committed_compute",
    "commit_failed": "rollback_recompute",
    "rollback": "rollback_recompute",
    "speculation_discarded": "rollback_recompute",
}

# Spans whose START is an attribution boundary: ambient time leading into a
# joiner's heal was LOST time (the process died/restarted/desynced — that
# is why it is healing), so it reads idle even when a post-heal commit
# follows in the same window. Donor-side heal_send is deliberately NOT a
# boundary: the donor's preceding ambient time was compute toward its own
# commit.
BOUNDARY_SPANS: Tuple[str, ...] = ("heal_recv",)

_RANK_BUCKET: Tuple[str, ...] = tuple(
    bucket for _, bucket in SPAN_BUCKETS
)
_SPAN_RANK: Dict[str, int] = {
    name: rank for rank, (name, _) in enumerate(SPAN_BUCKETS)
}
_N_RANKS = len(SPAN_BUCKETS)
_QUARANTINE_RANK = _SPAN_RANK["health_quarantine"]


def _env_float(name: str, default: float, floor: Optional[float] = None) -> float:
    try:
        value = float(os.environ.get(name, "") or default)
    except ValueError:
        value = default
    if floor is not None and value < floor:
        value = default
    return value


def _env_int(name: str, default: int, floor: Optional[int] = None) -> int:
    try:
        value = int(os.environ.get(name, "") or default)
    except ValueError:
        value = default
    if floor is not None and value < floor:
        value = default
    return value


def fold_events(
    events: Iterable[Dict[str, Any]], t0: float, t1: float
) -> Dict[str, float]:
    """Attributes the monotonic window ``[t0, t1]`` to :data:`BUCKETS`.

    Conservation-exact by construction: the window is cut at every mapped
    span edge and outcome instant, and each elementary segment is assigned
    exactly one bucket (highest-priority covering span, else the ambient
    rule above), so ``sum(result.values()) == t1 - t0`` to float epsilon.
    Events outside the window are ignored; spans straddling an edge are
    clipped. Tolerates ring drops (lost spans degrade to ambient time,
    never to a non-conserving total) and legacy quarantine ``served``
    instants that carry ``waited_s`` instead of a real span.
    """
    out = dict.fromkeys(BUCKETS, 0.0)
    if t1 <= t0:
        return out
    span_rank = _SPAN_RANK
    outcome_bucket = OUTCOME_BUCKETS
    marks: List[Tuple[float, int, int]] = []
    outcomes: List[Tuple[float, str]] = []
    for e in events:
        tm = e.get("t_mono")
        if tm is None:
            continue
        name = e.get("name")
        if e.get("ph") == "X":
            rank = span_rank.get(name)
            if rank is None:
                continue
            start = tm
            end = tm + float(e.get("dur") or 0.0)
        else:
            bucket = outcome_bucket.get(name)
            if bucket is not None:
                if t0 <= tm <= t1:
                    outcomes.append((tm, bucket))
                continue
            if name != "health_quarantine":
                continue
            args = e.get("args") or {}
            if args.get("phase") != "served":
                continue
            # Legacy journals recorded the quarantine serve as an instant
            # carrying waited_s; newer ones record the real span (which
            # takes the ph == "X" branch above).
            try:
                waited = float(args.get("waited_s") or 0.0)
            except (TypeError, ValueError):
                continue
            start = tm - waited
            end = tm
            rank = _QUARANTINE_RANK
        if end <= t0 or start >= t1:
            continue
        if start < t0:
            start = t0
        if end > t1:
            end = t1
        if end <= start:
            continue
        marks.append((start, 1, rank))
        marks.append((end, -1, rank))
        if name in BOUNDARY_SPANS:
            # The heal start fences the ambient lookahead: whatever the
            # replica was doing before it needed a heal, it did not commit.
            outcomes.append((start, "idle"))

    cut_set = {t0, t1}
    for t, _, _ in marks:
        cut_set.add(t)
    for t, _ in outcomes:
        cut_set.add(t)
    cuts = sorted(cut_set)
    marks.sort()
    outcomes.sort()
    otimes = [t for t, _ in outcomes]
    n_outcomes = len(otimes)
    counts = [0] * _N_RANKS
    mi = 0
    n_marks = len(marks)
    rank_bucket = _RANK_BUCKET
    for i in range(len(cuts) - 1):
        a = cuts[i]
        b = cuts[i + 1]
        while mi < n_marks and marks[mi][0] <= a:
            mark = marks[mi]
            counts[mark[2]] += mark[1]
            mi += 1
        bucket = None
        for rank in range(_N_RANKS):
            if counts[rank] > 0:
                bucket = rank_bucket[rank]
                break
        if bucket is None:
            # Ambient: the next outcome at-or-after this segment's end
            # names what the time was spent becoming; none -> idle.
            j = bisect_left(otimes, b)
            bucket = outcomes[j][1] if j < n_outcomes else "idle"
        out[bucket] += b - a
    return out


def top_badput(
    seconds: Dict[str, float], n: int = 2
) -> List[Tuple[str, float]]:
    """The ``n`` largest non-goodput buckets, largest first (zeros omitted)."""
    items = [
        (bucket, value)
        for bucket, value in seconds.items()
        if bucket != "committed_compute" and value > 0
    ]
    items.sort(key=lambda kv: (-kv[1], kv[0]))
    return items[:n]


class SloEvaluator:
    """Windowed goodput SLO with burn-rate hysteresis (health.py style).

    One :meth:`observe` per closed ledger window. ``burn_rate = badput /
    (1 - target)`` — the classic multi-window burn-rate framing: 1.0 means
    spending the error budget exactly at the sustained-violation rate,
    ``TPUFT_SLO_BURN_RATE`` scales the trip point. K consecutive burning
    windows (``TPUFT_SLO_WINDOWS``) latch exactly one breach — telemetry
    record on :data:`telemetry.slo_logger`, an ``slo_breach`` trace event,
    ``tpuft_slo_breaches_total``, and an incident auto-dump
    (:func:`tracing.open_incident`, kind ``slo_goodput``) — then stay
    latched until a healthy window re-arms, so a sustained burn pages once
    and a single-window blip never pages at all. Alerting only: nothing
    here ejects, raises past the step boundary, or touches actuation.
    """

    def __init__(
        self,
        target: float,
        windows: int = 3,
        burn_threshold: float = 1.0,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        if not 0.0 < float(target) <= 1.0:
            raise ValueError(f"SLO goodput target must be in (0, 1]: {target}")
        self.target = float(target)
        self.windows = max(1, int(windows))
        self.burn_threshold = float(burn_threshold)
        self.streak = 0
        self.latched = False
        self.breaches = 0
        self.last_burn_rate: float = 0.0
        self._labels = dict(labels or {})

    @classmethod
    def from_env(
        cls, labels: Optional[Dict[str, str]] = None
    ) -> Optional["SloEvaluator"]:
        """Builds the evaluator from ``TPUFT_SLO_*``; None when the SLO is
        unset or unparsable (doctor names the offender — a bad env must
        degrade to no-alerting, never break training)."""
        raw = os.environ.get(ENV_SLO_GOODPUT, "")
        if not raw:
            return None
        try:
            target = float(raw)
        except ValueError:
            return None
        if not 0.0 < target <= 1.0:
            return None
        return cls(
            target,
            windows=_env_int(ENV_SLO_WINDOWS, 3, floor=1),
            burn_threshold=_env_float(ENV_SLO_BURN_RATE, 1.0, floor=1e-9),
            labels=labels,
        )

    def observe(
        self,
        goodput: float,
        step: int = 0,
        quorum_id: int = -1,
        journal: Optional["tracing.TraceJournal"] = None,
    ) -> bool:
        """Scores one closed window; True when THIS window latches a breach."""
        budget = 1.0 - self.target
        badput = max(0.0, 1.0 - float(goodput))
        if budget <= 0.0:
            burn = math.inf if badput > 0 else 0.0
        else:
            burn = badput / budget
        self.last_burn_rate = burn
        metrics.set_gauge(
            "tpuft_slo_burn_rate",
            burn if math.isfinite(burn) else 1e9,
            slo="goodput",
            **self._labels,
        )
        burning = burn > self.burn_threshold
        if not burning:
            # A healthy window resets the streak AND re-arms the latch —
            # the next sustained burn pages again, a blip still cannot.
            self.streak = 0
            self.latched = False
            metrics.set_gauge(
                "tpuft_slo_burn_streak", 0, slo="goodput", **self._labels
            )
            return False
        self.streak += 1
        metrics.set_gauge(
            "tpuft_slo_burn_streak", self.streak, slo="goodput", **self._labels
        )
        if self.streak < self.windows or self.latched:
            return False
        self.latched = True
        self.breaches += 1
        self._fire(float(goodput), burn, step, quorum_id, journal)
        return True

    def _fire(
        self,
        goodput: float,
        burn: float,
        step: int,
        quorum_id: int,
        journal: Optional["tracing.TraceJournal"],
    ) -> None:
        j = journal or tracing.current()
        burn_out = round(burn, 4) if math.isfinite(burn) else "inf"
        metrics.inc("tpuft_slo_breaches_total", slo="goodput", **self._labels)
        try:
            telemetry.slo_logger.info(
                "slo_breach",
                extra={
                    "job_id": j.job_id,
                    "replica_id": j.replica_id,
                    "rank": j.group_rank,
                    "quorum_id": quorum_id,
                    "step": step,
                    "slo": "goodput",
                    "slo_target": self.target,
                    "burn_rate": burn_out,
                    "goodput": round(goodput, 6),
                    "windows": self.streak,
                },
            )
        except Exception:  # noqa: BLE001 — exporter failures never escape
            pass
        j.record(
            "slo_breach",
            cat="slo",
            step=step,
            quorum_id=quorum_id,
            slo="goodput",
            target=self.target,
            burn_rate=burn_out,
            goodput=round(goodput, 6),
            windows=self.streak,
        )
        tracing.open_incident(
            "slo_goodput",
            step,
            quorum_id,
            journal=j,
            reason=(
                f"goodput {goodput:.4f} below target {self.target} for "
                f"{self.streak} consecutive windows (burn {burn_out})"
            ),
        )

    def status(self) -> Dict[str, Any]:
        return {
            "slo": "goodput",
            "target": self.target,
            "windows": self.windows,
            "burn_threshold": self.burn_threshold,
            "burn_rate": (
                round(self.last_burn_rate, 4)
                if math.isfinite(self.last_burn_rate)
                else None
            ),
            "streak": self.streak,
            "latched": self.latched,
            "breaches": self.breaches,
        }


class GoodputLedger:
    """Per-replica goodput accounting riding the metrics-push cadence.

    Holds an open window starting where the last one closed; ``collect``
    (called from ``Manager._push_metrics``, i.e. every push) closes it
    once it is at least ``TPUFT_GOODPUT_WINDOW_SEC`` wide, folds the trace
    ring over it, retains the window in a byte-budgeted
    :class:`metrics.WindowedSeries`, counts ``tpuft_goodput_seconds_total``
    per bucket, gauges the rolling ``tpuft_goodput_fraction``, scores the
    SLO, and returns the store-push payload. All clocks come from the
    journal (injectable), so threads-as-replicas drills replay scripted
    timelines deterministically. With the trace plane disabled
    (``TPUFT_TRACE=0``) the ledger degrades to an explicit
    ``{"enabled": False}`` payload — never a silently-idle fleet.
    """

    def __init__(
        self,
        journal: Optional["tracing.TraceJournal"] = None,
        window_sec: Optional[float] = None,
        max_windows: Optional[int] = None,
        max_bytes: Optional[int] = None,
        slo: Optional[SloEvaluator] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        self._journal = journal if journal is not None else tracing.current()
        self._window_sec = (
            window_sec
            if window_sec is not None
            else _env_float(ENV_WINDOW_SEC, 5.0, floor=1e-3)
        )
        self._series = metrics.WindowedSeries(
            max_windows=(
                max_windows
                if max_windows is not None
                else _env_int(ENV_WINDOWS, 60, floor=1)
            ),
            max_bytes=(
                max_bytes
                if max_bytes is not None
                else _env_int(ENV_BYTES, 262144, floor=1024)
            ),
        )
        self._slo = slo if slo is not None else SloEvaluator.from_env(labels)
        self._labels = dict(labels or {})
        self._t0 = self._journal._mono()
        self._totals = dict.fromkeys(BUCKETS, 0.0)

    @property
    def slo(self) -> Optional[SloEvaluator]:
        return self._slo

    @property
    def series(self) -> "metrics.WindowedSeries":
        return self._series

    def collect(
        self,
        now_mono: Optional[float] = None,
        step: Optional[int] = None,
        quorum_id: Optional[int] = None,
        force: bool = False,
    ) -> Dict[str, Any]:
        """Closes the open window when due (or ``force``); returns the
        payload either way. Never raises — this rides the metrics push."""
        journal = self._journal
        if not journal.enabled:
            return {"enabled": False}
        try:
            now = journal._mono() if now_mono is None else now_mono
            due = (now - self._t0) >= self._window_sec
            if (due or force) and now > self._t0:
                self._close_window(now, step, quorum_id)
        except Exception:  # noqa: BLE001 — observability must not wound
            pass
        return self.payload()

    def _close_window(
        self, now: float, step: Optional[int], quorum_id: Optional[int]
    ) -> None:
        journal = self._journal
        seconds = fold_events(journal._copy_ring(), self._t0, now)
        duration = now - self._t0
        goodput = seconds["committed_compute"] / duration if duration > 0 else 0.0
        window = {
            "t0": round(self._t0, 6),
            "t1": round(now, 6),
            "wall": journal._wall(),
            "step": journal.step if step is None else step,
            "goodput": round(goodput, 6),
            "seconds": {b: round(s, 6) for b, s in seconds.items() if s > 0},
        }
        self._t0 = now
        self._series.append(window)
        for bucket, value in seconds.items():
            self._totals[bucket] += value
            if value > 0:
                metrics.inc(
                    "tpuft_goodput_seconds_total",
                    value,
                    bucket=bucket,
                    **self._labels,
                )
        metrics.inc("tpuft_goodput_windows_total", **self._labels)
        metrics.set_gauge(
            "tpuft_goodput_series_bytes",
            self._series.total_bytes(),
            **self._labels,
        )
        rolling = self.rolling_goodput()
        if rolling is not None:
            metrics.set_gauge(
                "tpuft_goodput_fraction", rolling, **self._labels
            )
        if self._slo is not None:
            self._slo.observe(
                goodput,
                step=window["step"],
                quorum_id=(
                    journal.quorum_id if quorum_id is None else quorum_id
                ),
                journal=journal,
            )

    def _aggregate(self) -> Dict[str, float]:
        agg = dict.fromkeys(BUCKETS, 0.0)
        for window in self._series.windows():
            for bucket, value in (window.get("seconds") or {}).items():
                if bucket in agg:
                    agg[bucket] += value
        return agg

    def rolling_goodput(self) -> Optional[float]:
        """Goodput fraction over the retained window ring (None until the
        first window closes) — the stable headline the GOODPUT column and
        the bench line read, vs. a single window's noise."""
        agg = self._aggregate()
        total = sum(agg.values())
        if total <= 0:
            return None
        return agg["committed_compute"] / total

    def payload(self, max_windows: int = 30) -> Dict[str, Any]:
        """The store-push / report payload: rolling aggregate + the most
        recent windows (bounded — the series ring itself is the live
        local view, the push only needs enough for fleet merging)."""
        if not self._journal.enabled:
            return {"enabled": False}
        agg = self._aggregate()
        total = sum(agg.values())
        payload: Dict[str, Any] = {
            "enabled": True,
            "window_sec": self._window_sec,
            "goodput": round(agg["committed_compute"] / total, 6)
            if total > 0
            else None,
            "seconds": {b: round(s, 6) for b, s in agg.items() if s > 0},
            "totals": {
                b: round(s, 6) for b, s in self._totals.items() if s > 0
            },
            "windows": self._series.windows()[-max_windows:],
        }
        if self._slo is not None:
            payload["slo"] = self._slo.status()
        return payload


def merge_windows(
    snapshots: Sequence[Dict[str, Any]]
) -> Dict[str, Any]:
    """Merges per-replica goodput payloads into one fleet accounting.

    ``snapshots`` are metrics-push snapshot dicts (``{"replica_id", ...,
    "region", "goodput": payload}`` as fleet_status collects them) or bare
    ledger payloads. Returns fleet totals, the fleet goodput fraction, a
    per-cause badput breakdown (largest first), and per-region /
    per-replica splits (regions ride the PR-16 topology labels)."""
    agg = dict.fromkeys(BUCKETS, 0.0)
    regions: Dict[str, Dict[str, float]] = {}
    per_replica: Dict[str, Dict[str, Any]] = {}
    replicas = 0
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        nested = snap.get("goodput")
        payload = nested if isinstance(nested, dict) else snap
        if not payload.get("enabled", True):
            continue
        seconds = payload.get("seconds") or {}
        if not isinstance(seconds, dict) or not seconds:
            continue
        replicas += 1
        replica_id = str(snap.get("replica_id", f"replica{replicas}"))
        region = str(snap.get("region") or "unknown")
        region_agg = regions.setdefault(region, dict.fromkeys(BUCKETS, 0.0))
        local = dict.fromkeys(BUCKETS, 0.0)
        for bucket, value in seconds.items():
            if bucket in agg:
                value = float(value)
                agg[bucket] += value
                region_agg[bucket] += value
                local[bucket] += value
        local_total = sum(local.values())
        per_replica[replica_id] = {
            "region": region,
            "goodput": round(local["committed_compute"] / local_total, 6)
            if local_total > 0
            else None,
            "seconds": {b: round(s, 6) for b, s in local.items() if s > 0},
        }
    total = sum(agg.values())
    badput = [
        {
            "bucket": bucket,
            "seconds": round(value, 6),
            "fraction": round(value / total, 6) if total > 0 else 0.0,
        }
        for bucket, value in top_badput(agg, n=len(BUCKETS))
    ]
    region_out = {}
    for region, region_agg in sorted(regions.items()):
        region_total = sum(region_agg.values())
        region_out[region] = {
            "goodput": round(
                region_agg["committed_compute"] / region_total, 6
            )
            if region_total > 0
            else None,
            "seconds": {
                b: round(s, 6) for b, s in region_agg.items() if s > 0
            },
        }
    return {
        "replicas": replicas,
        "wall_seconds": round(total, 6),
        "goodput": round(agg["committed_compute"] / total, 6)
        if total > 0
        else None,
        "seconds": {b: round(s, 6) for b, s in agg.items() if s > 0},
        "badput": badput,
        "regions": region_out,
        "per_replica": per_replica,
    }
