"""Gray-failure ejection plane: slow-is-the-new-dead straggler verdicts.

The quorum model is binary — a replica is heartbeating or it is dead —
but the worst production failures are gray: a replica whose device
wedges mid-run, whose NIC drips, or whose host is oversubscribed keeps
heartbeating and voting while dragging every commit barrier to its
speed (this machine's axon relay exhibits all three modes; CLAUDE.md).
The fleet already *measures* the signal — per-phase histograms, the
trace plane's per-step phase rollup, fleet_status's STRAGGLER column —
this module closes the loop from evidence to safe actuation:

- :class:`HealthScorer` — per-replica EWMAs of the existing phase
  evidence (device_sync / update_dispatch / wire_bucket), compared
  fleet-relatively against peer snapshots pushed through the quorum's
  shared store (the same plumbing the metrics push rides). A verdict
  requires ``TPUFT_HEALTH_CONSECUTIVE`` consecutive windows beyond a
  multiplicative threshold vs the fleet median AND an absolute gap
  floor — hysteresis: a transient blip must never eject.
- **Self-ejection** — a replica judging itself degraded funnels a
  :class:`DegradedReplicaError` into ``Manager.report_error`` and then
  raises it out of ``start_quorum`` at the step boundary: the same
  supervisor-escalation family as quorum timeouts and
  ``HealExhaustedError``. The survivors see an ordinary membership
  change (window drain → pg.configure → proceed) and the ejected
  replica rejoins via the normal heal path once its self-probe passes
  (delta rejoin makes the comeback cheap).
- :class:`StepWatchdog` — the fully-wedged case: device sync never
  completes but the control thread keeps heartbeating. A step-progress
  deadline scaled from the replica's OWN step-interval EWMA trips the
  same probe→eject path from a watchdog thread (the train thread is
  stuck, so escalation defaults to SIGTERM — the supervisor restarts
  the process and the quarantine gate takes over).
- :class:`QuarantineGate` — re-probe with exponential backoff
  (``TPUFT_QUARANTINE_BASE_SEC``, capped), and ``M`` ejections inside a
  sliding window parks the replica until a long cooldown — a
  crash-looping gray host cannot flap the fleet. State persists across
  supervised restarts (keyed by the STABLE replica id).
- **Peer accusations stay advisory**: barrier-wait asymmetry (the rank
  that waited least entered last) is published to the metrics plane and
  surfaced in fleet_status / ``fleet_trace --explain-step``, but a peer
  NEVER initiates a kill — a partition cannot brain-split the fleet
  into mutual ejections. Only self-verdicts actuate.

Chaos seams (:func:`injected_stall`): the punisher arms
``slow_replica`` / ``wedge_device`` (site ``device_sync``) and
``drip_wire`` (site ``wire``) through the fault file
(utils/faultinject.py). One arm = one replica affected: the consuming
replica installs a PERSISTENT per-replica stall/wedge keyed by its
trace-journal identity (threads-as-replicas drills give each replica
thread its own journal), cleared by ejection — exactly like a process
restart clears real module state.

Safety invariants:

- Ejection below ``min_replica_size`` is REFUSED and counted
  (``tpuft_health_ejections_refused_total``) — a degraded fleet keeps
  training slowly rather than deadlocking the quorum.
- Everything store/metrics-side is best-effort: a dead board or a
  failed push can never wound a step. Only the explicit ejection raise
  leaves the step boundary.

docs/resilience.md rows; docs/observability.md walkthrough;
drills in tests/test_health.py; benchmarks/straggler_bench.py.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchft_tpu import metrics, tracing
from torchft_tpu.utils import faultinject

logger = logging.getLogger(__name__)

__all__ = [
    "DegradedReplicaError",
    "HealthScorer",
    "StepWatchdog",
    "QuarantineGate",
    "HealthMonitor",
    "enabled",
    "injected_stall",
    "install_injected",
    "clear_injected",
    "SELF_PHASES",
]

# -- env knobs (doctor.KNOWN_ENV mirrors every name here) -------------------
ENV_HEALTH = "TPUFT_HEALTH"
ENV_THRESHOLD = "TPUFT_HEALTH_THRESHOLD"
ENV_CONSECUTIVE = "TPUFT_HEALTH_CONSECUTIVE"
ENV_MIN_PEERS = "TPUFT_HEALTH_MIN_PEERS"
ENV_EWMA_ALPHA = "TPUFT_HEALTH_EWMA_ALPHA"
ENV_PEER_TTL = "TPUFT_HEALTH_PEER_TTL_SEC"
ENV_PUSH_SEC = "TPUFT_HEALTH_PUSH_SEC"
ENV_MIN_GAP = "TPUFT_HEALTH_MIN_GAP_SEC"
ENV_WEDGE_SCALE = "TPUFT_HEALTH_WEDGE_SCALE"
ENV_WEDGE_FLOOR = "TPUFT_HEALTH_WEDGE_FLOOR_SEC"
ENV_WEDGE_ACTION = "TPUFT_HEALTH_WEDGE_ACTION"  # term | flag
ENV_SLOW_MS = "TPUFT_HEALTH_SLOW_MS"
ENV_PROBE = "TPUFT_HEALTH_PROBE"
ENV_PROBE_TIMEOUT = "TPUFT_HEALTH_PROBE_TIMEOUT_SEC"
ENV_QUARANTINE_BASE = "TPUFT_QUARANTINE_BASE_SEC"
ENV_QUARANTINE_CAP = "TPUFT_QUARANTINE_CAP_SEC"
ENV_QUARANTINE_MAX_EJECTS = "TPUFT_QUARANTINE_MAX_EJECTS"
ENV_QUARANTINE_WINDOW = "TPUFT_QUARANTINE_WINDOW_SEC"
ENV_QUARANTINE_PARK = "TPUFT_QUARANTINE_PARK_SEC"
ENV_QUARANTINE_DIR = "TPUFT_QUARANTINE_DIR"

# Phases a replica scores ITSELF on (own work being slow = I am the
# straggler). The commit-barrier wait is the INVERSE signal — the rank
# that waited least entered last — and feeds peer accusations only.
SELF_PHASES = ("device_sync", "update_dispatch", "wire_bucket")
BARRIER_PHASE = "commit_barrier"

# tpuft_health_state gauge values (fleet_status's HEALTH column decodes).
STATE_HEALTHY = 0
STATE_SUSPECT = 1
STATE_DEGRADED = 2
STATE_QUARANTINED = 3
STATE_PARKED = 4
STATE_NAMES = {
    STATE_HEALTHY: "ok",
    STATE_SUSPECT: "suspect",
    STATE_DEGRADED: "degraded",
    STATE_QUARANTINED: "quar",
    STATE_PARKED: "parked",
}

# Well-known shared-store key prefix for pushed health snapshots (the
# quorum's rendezvous store, which every member can already reach).
BOARD_PREFIX = "health"


class DegradedReplicaError(RuntimeError):
    """Raised out of ``Manager.start_quorum`` at the step boundary when
    this replica's health verdict (or the wedge watchdog) judged it
    degraded: slow-is-the-new-dead. Same escalation family as a quorum
    timeout or :class:`~torchft_tpu.manager.HealExhaustedError` — the
    supervisor restarts the process, the quarantine gate re-probes the
    accelerator with exponential backoff, and the replica rejoins
    through the normal heal path (delta rejoin) once the probe passes."""


def _env_float(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


def _env_int(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


def enabled() -> bool:
    """Master switch: the Manager auto-attaches a monitor iff set."""
    return os.environ.get(ENV_HEALTH, "0") not in ("", "0")


# ---------------------------------------------------------------------------
# chaos seams: punisher-armed persistent gray faults
# ---------------------------------------------------------------------------

# Per-replica injected gray state, keyed by the trace journal identity of
# the consuming thread (threads-as-replicas drills give each replica its
# own journal; a real process has exactly one). Module-global on purpose:
# real gray failures are per-PROCESS, and ejection/restart clears them.
_INJECTED_LOCK = threading.Lock()
_INJECTED: Dict[str, Dict[str, Any]] = {}

# Fault modes -> the sites their installed stall applies to.
_INJECT_MODES = {
    "slow_replica": ("device_sync",),
    "wedge_device": ("device_sync",),
    "drip_wire": ("wire",),
}


def _replica_key() -> str:
    return tracing.current().replica_id


def install_injected(
    mode: str, replica_id: Optional[str] = None, stall_s: Optional[float] = None
) -> None:
    """Installs a persistent gray fault for ``replica_id`` (default: the
    calling thread's journal identity). ``slow_replica``/``drip_wire``
    stall every matching phase by ``stall_s`` (default
    ``$TPUFT_HEALTH_SLOW_MS``); ``wedge_device`` blocks the device sync
    until :func:`clear_injected` — the fully-wedged mode the step
    watchdog exists for."""
    if mode not in _INJECT_MODES:
        raise ValueError(f"unknown injected gray mode {mode!r}")
    key = replica_id if replica_id is not None else _replica_key()
    state: Dict[str, Any] = {"mode": mode, "sites": set(_INJECT_MODES[mode])}
    if mode == "wedge_device":
        state["released"] = threading.Event()
    else:
        state["stall_s"] = (
            stall_s
            if stall_s is not None
            else _env_float(ENV_SLOW_MS, 250.0) / 1000.0
        )
    with _INJECTED_LOCK:
        _INJECTED[key] = state
    metrics.inc("tpuft_health_injected_faults_total", mode=mode)
    tracing.record("health_fault_injected", mode=mode, replica=key)
    logger.warning("health chaos: installed %s for replica %s", mode, key)


def clear_injected(replica_id: Optional[str] = None) -> None:
    """Clears injected gray faults (one replica, or all when None) —
    what a process restart does for free; the thread drills and the
    ejection path call it explicitly. Releases any wedge waiter."""
    with _INJECTED_LOCK:
        keys = [replica_id] if replica_id is not None else list(_INJECTED)
        for key in keys:
            state = _INJECTED.pop(key, None)
            if state is not None and state.get("released") is not None:
                state["released"].set()


def injected_stall(site: str) -> None:
    """The gray-fault chokepoint, called from the device-sync and wire
    seams (optim._sync_device, ddp's bucket wait). Production cost when
    unarmed: one env lookup + one dict get. A punisher arm at this site
    is consumed exactly once (faultinject semantics) and INSTALLS the
    persistent per-replica fault; every later call applies it."""
    if os.environ.get(faultinject.ENV_FAULT_FILE):
        mode = faultinject.consume(site)
        if mode in _INJECT_MODES:
            install_injected(mode)
    state = _INJECTED.get(_replica_key())
    if not state or site not in state["sites"]:
        return
    released = state.get("released")
    if released is not None:
        # Wedge: the device never answers. Blocks until ejection/restart
        # clears the fault (clear_injected sets the event) — meanwhile
        # the control threads keep heartbeating, which is the point.
        released.wait()
        return
    stall = float(state.get("stall_s", 0.0))
    if stall > 0.0:
        time.sleep(stall)


# ---------------------------------------------------------------------------
# scorer
# ---------------------------------------------------------------------------


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


class HealthScorer:
    """Pure verdict logic: own per-phase EWMAs vs fleet-relative peer
    snapshots, with hysteresis. No I/O, no threads — the monitor owns
    plumbing, the bench and unit tests drive this directly.

    A window is "slow" when ANY self phase satisfies BOTH bounds against
    the fleet median of fresh peers: ``own > threshold * median`` (the
    multiplicative bound — fleet-relative, so a uniformly slow fleet
    never accuses anyone) and ``own - median > min_gap_s`` (the absolute
    floor — 3x a microsecond-scale phase is noise, not a verdict).
    ``consecutive`` slow windows latch the degraded verdict; one healthy
    window resets the streak — transient blips never eject."""

    def __init__(
        self,
        replica_id: str,
        threshold: Optional[float] = None,
        consecutive: Optional[int] = None,
        min_peers: Optional[int] = None,
        alpha: Optional[float] = None,
        peer_ttl_s: Optional[float] = None,
        min_gap_s: Optional[float] = None,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self.replica_id = replica_id
        self.threshold = max(
            1.01, threshold if threshold is not None else _env_float(ENV_THRESHOLD, 3.0)
        )
        self.consecutive = max(
            1,
            consecutive
            if consecutive is not None
            else _env_int(ENV_CONSECUTIVE, 3),
        )
        self.min_peers = max(
            1, min_peers if min_peers is not None else _env_int(ENV_MIN_PEERS, 2)
        )
        self.alpha = min(
            1.0, max(0.01, alpha if alpha is not None else _env_float(ENV_EWMA_ALPHA, 0.25))
        )
        self.peer_ttl_s = (
            peer_ttl_s if peer_ttl_s is not None else _env_float(ENV_PEER_TTL, 60.0)
        )
        self.min_gap_s = (
            min_gap_s if min_gap_s is not None else _env_float(ENV_MIN_GAP, 0.05)
        )
        self._wall = wall
        self.ewma: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self._peers: Dict[str, Tuple[float, Dict[str, float]]] = {}
        self.streak = 0
        self._rollup_seen_step = -1

    # -- own evidence -------------------------------------------------------

    def observe(self, phase: str, seconds: float) -> None:
        prev = self.ewma.get(phase)
        value = max(float(seconds), 0.0)
        self.ewma[phase] = (
            value if prev is None else prev + self.alpha * (value - prev)
        )
        self.counts[phase] = self.counts.get(phase, 0) + 1

    def ingest_rollup(self, rollup: List[Dict[str, Any]]) -> None:
        """Feeds the trace plane's per-step phase rollup
        (TraceJournal.phase_rollup) — the EXISTING per-phase evidence —
        into the EWMAs, each step at most once."""
        for entry in rollup:
            step = entry.get("step")
            if step is None or step <= self._rollup_seen_step:
                continue
            phases = entry.get("phases") or {}
            for phase in SELF_PHASES + (BARRIER_PHASE,):
                if phase in phases:
                    self.observe(phase, float(phases[phase]))
            self._rollup_seen_step = step

    # -- peer snapshots -----------------------------------------------------

    def note_peer(
        self, replica_id: str, phases: Dict[str, float], ts: Optional[float] = None
    ) -> None:
        if replica_id == self.replica_id:
            return
        self._peers[replica_id] = (
            self._wall() if ts is None else float(ts),
            {k: float(v) for k, v in phases.items()},
        )

    def fresh_peers(self) -> Dict[str, Dict[str, float]]:
        now = self._wall()
        return {
            rid: phases
            for rid, (ts, phases) in self._peers.items()
            if now - ts <= self.peer_ttl_s
        }

    def snapshot(self) -> Dict[str, Any]:
        """The pushed board payload — what peers score us against."""
        return {
            "ts": self._wall(),
            "replica_id": self.replica_id,
            "phases": {k: round(v, 6) for k, v in self.ewma.items()},
            "streak": self.streak,
        }

    # -- verdict ------------------------------------------------------------

    def evaluate(self) -> Dict[str, Any]:
        """One scoring window. Returns the verdict dict; hysteresis state
        (the streak) advances only on judgeable windows."""
        peers = self.fresh_peers()
        verdict: Dict[str, Any] = {
            "judgeable": False,
            "slow": False,
            "degraded": False,
            "streak": self.streak,
            "ratios": {},
            "peers": len(peers),
        }
        if len(peers) < self.min_peers:
            return verdict
        slow = False
        for phase in SELF_PHASES:
            own = self.ewma.get(phase)
            if own is None or self.counts.get(phase, 0) < 2:
                continue
            fleet = [p[phase] for p in peers.values() if phase in p]
            if len(fleet) < self.min_peers:
                continue
            med = _median(fleet)
            ratio = own / max(med, 1e-9)
            verdict["ratios"][phase] = round(ratio, 3)
            verdict["judgeable"] = True
            if ratio > self.threshold and (own - med) > self.min_gap_s:
                slow = True
        if not verdict["judgeable"]:
            return verdict
        self.streak = self.streak + 1 if slow else 0
        verdict.update(
            slow=slow, streak=self.streak, degraded=self.streak >= self.consecutive
        )
        return verdict

    def accuse(self) -> Optional[Tuple[str, float]]:
        """ADVISORY straggler attribution from barrier-wait asymmetry:
        the commit barrier releases everyone together, so the member
        with the SMALLEST barrier wait entered last and held the fleet
        up. Returns ``(accused_replica_id, gap_seconds)`` when the
        asymmetry clears both the multiplicative and absolute bounds, or
        None. Never actuates — accusations are published for operators
        (fleet_status / explain-step), not for peers to act on."""
        waits: Dict[str, float] = {}
        own = self.ewma.get(BARRIER_PHASE)
        if own is not None and self.counts.get(BARRIER_PHASE, 0) >= 2:
            waits[self.replica_id] = own
        for rid, phases in self.fresh_peers().items():
            if BARRIER_PHASE in phases:
                waits[rid] = phases[BARRIER_PHASE]
        if len(waits) < max(self.min_peers + 1, 2):
            return None
        slowest = min(waits, key=lambda r: waits[r])  # least wait = entered last
        longest = max(waits.values())
        gap = longest - waits[slowest]
        if longest > self.threshold * max(waits[slowest], 1e-9) and gap > self.min_gap_s:
            return slowest, gap
        return None


# ---------------------------------------------------------------------------
# step-progress watchdog (the fully-wedged case)
# ---------------------------------------------------------------------------


class StepWatchdog:
    """Fires ``on_wedge(elapsed_s, deadline_s)`` once when no step
    progress (:meth:`beat`) lands within a deadline scaled from the
    replica's OWN step-interval EWMA — ``max(scale * interval_ewma,
    floor)``, the floor alone before any interval evidence exists. The
    whole point is the case the scorer cannot see: a device sync that
    never completes parks the train thread forever while heartbeats
    keep the replica in the quorum. Re-arms on the next beat."""

    def __init__(
        self,
        on_wedge: Callable[[float, float], None],
        scale: Optional[float] = None,
        floor_s: Optional[float] = None,
        mono: Callable[[], float] = time.monotonic,
        alpha: float = 0.25,
    ) -> None:
        self._on_wedge = on_wedge
        self.scale = max(
            1.5, scale if scale is not None else _env_float(ENV_WEDGE_SCALE, 10.0)
        )
        self.floor_s = max(
            0.05,
            floor_s if floor_s is not None else _env_float(ENV_WEDGE_FLOOR, 30.0),
        )
        self._mono = mono
        self._alpha = alpha
        self._lock = threading.Lock()
        self._last_beat: Optional[float] = None
        self.interval_ewma: Optional[float] = None
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def deadline_s(self) -> float:
        with self._lock:
            if self.interval_ewma is None:
                return self.floor_s
            return max(self.scale * self.interval_ewma, self.floor_s)

    def beat(self) -> None:
        now = self._mono()
        with self._lock:
            if self._last_beat is not None:
                dt = now - self._last_beat
                self.interval_ewma = (
                    dt
                    if self.interval_ewma is None
                    else self.interval_ewma + self._alpha * (dt - self.interval_ewma)
                )
            self._last_beat = now
            self._fired = False
        if self._thread is None:
            self.start()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tpuft-health-watchdog"
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            deadline = self.deadline_s()
            self._stop.wait(min(max(deadline / 4.0, 0.05), 1.0))
            with self._lock:
                last = self._last_beat
                fired = self._fired
            if last is None or fired:
                continue
            elapsed = self._mono() - last
            if elapsed > deadline:
                with self._lock:
                    self._fired = True
                try:
                    self._on_wedge(elapsed, deadline)
                except Exception:  # noqa: BLE001 — the watchdog must survive
                    logger.exception("wedge callback failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ---------------------------------------------------------------------------
# quarantine gate
# ---------------------------------------------------------------------------


def _default_probe() -> bool:
    """The self-check a quarantined replica must pass before rejoining:
    a full compile→execute→fetch round trip in a disposable subprocess
    (utils/platform.probe_accelerator — the relay's wedge modes hang
    in-process probes, which is exactly what this gate exists to catch).
    ``TPUFT_HEALTH_PROBE=0`` skips it (drills / CPU-only fleets)."""
    if os.environ.get(ENV_PROBE, "1") == "0":
        return True
    from torchft_tpu.utils.platform import probe_accelerator

    return probe_accelerator(timeout=_env_float(ENV_PROBE_TIMEOUT, 120.0))


class QuarantineGate:
    """Ejection bookkeeping + the startup re-admission gate.

    Every ejection is recorded (persisted under
    ``$TPUFT_QUARANTINE_DIR`` — default the flight-recorder dir — so
    supervised restarts of the same replica see it). :meth:`serve`
    re-probes with exponential backoff (``base * 2^attempt``, capped)
    until the probe passes; ``max_ejects`` ejections inside the sliding
    ``window_s`` parks the replica for ``park_s`` first — the
    crash-loop fence. All waiting is injectable for tests."""

    def __init__(
        self,
        replica_id: str,
        base_s: Optional[float] = None,
        cap_s: Optional[float] = None,
        max_ejects: Optional[int] = None,
        window_s: Optional[float] = None,
        park_s: Optional[float] = None,
        state_dir: Optional[str] = None,
        probe: Optional[Callable[[], bool]] = None,
        sleep: Callable[[float], None] = time.sleep,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self.replica_id = replica_id
        self.base_s = max(
            0.01, base_s if base_s is not None else _env_float(ENV_QUARANTINE_BASE, 1.0)
        )
        self.cap_s = max(
            self.base_s,
            cap_s if cap_s is not None else _env_float(ENV_QUARANTINE_CAP, 60.0),
        )
        self.max_ejects = max(
            1,
            max_ejects
            if max_ejects is not None
            else _env_int(ENV_QUARANTINE_MAX_EJECTS, 3),
        )
        self.window_s = (
            window_s if window_s is not None else _env_float(ENV_QUARANTINE_WINDOW, 900.0)
        )
        self.park_s = (
            park_s if park_s is not None else _env_float(ENV_QUARANTINE_PARK, 1800.0)
        )
        self._probe = probe if probe is not None else _default_probe
        self._sleep = sleep
        self._wall = wall
        if state_dir is None:
            state_dir = os.environ.get(ENV_QUARANTINE_DIR) or os.environ.get(
                "TPUFT_FLIGHT_RECORDER"
            )
        self._state_path: Optional[str] = None
        if state_dir:
            try:
                os.makedirs(state_dir, exist_ok=True)
                self._state_path = os.path.join(
                    state_dir, f"quarantine_{tracing.sanitize(replica_id)}.json"
                )
            except OSError:
                self._state_path = None
        self.ejections: List[float] = []
        self.last_reason = ""
        self._load()

    # -- persistence --------------------------------------------------------

    def _load(self) -> None:
        if not self._state_path:
            return
        try:
            with open(self._state_path, "r") as f:
                data = json.load(f)
            self.ejections = [float(t) for t in data.get("ejections", [])]
            self.last_reason = str(data.get("last_reason", ""))
        except (OSError, ValueError):
            pass

    def _save(self) -> None:
        if not self._state_path:
            return
        try:
            tmp = f"{self._state_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(
                    {"ejections": self.ejections, "last_reason": self.last_reason}, f
                )
            os.replace(tmp, self._state_path)
        except OSError:
            pass

    # -- accounting ---------------------------------------------------------

    def _recent(self) -> List[float]:
        now = self._wall()
        return [t for t in self.ejections if now - t <= self.window_s]

    def record_ejection(self, reason: str) -> None:
        self.ejections = self._recent() + [self._wall()]
        self.last_reason = reason
        self._save()

    def pending(self) -> bool:
        """True when a recent ejection is on file — the restarted
        process must serve quarantine before rejoining the fleet."""
        return bool(self._recent())

    def parked_until(self) -> float:
        """Nonzero wall time when the crash-loop fence is up: the
        sliding window holds ``max_ejects`` ejections, so re-admission
        waits out the long cooldown from the LAST ejection."""
        recent = self._recent()
        if len(recent) >= self.max_ejects:
            return max(recent) + self.park_s
        return 0.0

    # -- the gate -----------------------------------------------------------

    def serve(
        self, trace: Optional["tracing.TraceJournal"] = None, max_attempts: int = 64
    ) -> Dict[str, Any]:
        """Blocks until re-admission: park cooldown (if the crash-loop
        fence is up), then probe with exponential backoff until it
        passes. Returns the served record; counts
        ``tpuft_health_quarantine_seconds_total`` / ``_probes_total`` /
        ``_parked_total``. ``max_attempts`` bounds a probe that can
        never pass (the capped backoff keeps waiting cheap; past the
        bound we admit and let the verdict plane re-eject — an operator
        signal, not an infinite coma)."""
        journal = trace or tracing.current()
        waited = 0.0
        parked = False
        park_until = self.parked_until()
        if park_until > 0:
            parked = True
            metrics.inc("tpuft_health_parked_total")
            remaining = max(park_until - self._wall(), 0.0)
            journal.record(
                "health_quarantine", phase="parked", wait_s=round(remaining, 3),
                ejections=len(self._recent()),
            )
            logger.warning(
                "replica %s crash-loop parked: %d ejections in %.0fs window; "
                "cooling down %.1fs",
                self.replica_id, len(self._recent()), self.window_s, remaining,
            )
            self._sleep(remaining)
            waited += remaining
        attempts = 0
        while True:
            delay = min(self.base_s * (2.0 ** attempts), self.cap_s)
            self._sleep(delay)
            waited += delay
            ok = False
            try:
                ok = bool(self._probe())
            except Exception:  # noqa: BLE001 — a probe crash is a fail
                logger.exception("quarantine probe raised (counted as fail)")
            metrics.inc(
                "tpuft_health_probes_total", result="pass" if ok else "fail"
            )
            attempts += 1
            journal.record(
                "health_quarantine", phase="probe", attempt=attempts,
                result="pass" if ok else "fail", backoff_s=round(delay, 3),
            )
            if ok or attempts >= max_attempts:
                break
        metrics.inc("tpuft_health_quarantine_seconds_total", waited)
        record = {
            "attempts": attempts,
            "waited_s": round(waited, 3),
            "parked": parked,
        }
        # A real span (start backdated by the wait), not an instant: the
        # goodput ledger folds it into the `degraded` bucket; fleet_trace
        # keeps reading the same args off the served record.
        journal.record(
            "health_quarantine", ph="X", dur=waited, phase="served", **record
        )
        return record


# ---------------------------------------------------------------------------
# the monitor (glue: manager-side AND bench-side host)
# ---------------------------------------------------------------------------


class HealthMonitor:
    """One replica's verdict loop: scorer + watchdog + quarantine gate +
    the board plumbing, driven from the step boundary.

    The Manager calls :meth:`on_quorum` (peer set + shared board),
    :meth:`on_step` (cheap, never raises) after every commit
    resolution, and :meth:`should_eject` at the next ``start_quorum`` —
    the ONLY place the plane leaves the step boundary. The straggler
    bench drives the same object with a dict board and injected clocks.
    """

    def __init__(
        self,
        replica_id: str,
        group_rank: int = 0,
        min_replica_size: int = 1,
        scorer: Optional[HealthScorer] = None,
        gate: Optional[QuarantineGate] = None,
        watchdog: Optional[StepWatchdog] = None,
        board: Optional[Any] = None,
        trace: Optional["tracing.TraceJournal"] = None,
        push_interval_s: Optional[float] = None,
        wedge_action: Optional[Callable[[], None]] = None,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self.replica_id = replica_id
        self.group_rank = int(group_rank)
        self.min_replica_size = int(min_replica_size)
        self.scorer = scorer or HealthScorer(replica_id, wall=wall)
        self.gate = gate or QuarantineGate(replica_id, wall=wall)
        self._watchdog = watchdog
        if self._watchdog is None:
            self._watchdog = StepWatchdog(self._on_wedge)
        else:
            self._watchdog._on_wedge = self._on_wedge
        self._board = board
        # An explicitly injected board (bench/tests) is pinned: quorum
        # discovery must not silently swap it for a store client.
        self._board_pinned = board is not None
        self._board_addr: Optional[str] = None
        self._peer_ids: List[str] = []
        self._participants = 0
        self._trace = trace
        self._wall = wall
        self._push_interval = (
            push_interval_s
            if push_interval_s is not None
            else _env_float(ENV_PUSH_SEC, 2.0)
        )
        self._last_push = 0.0
        self._wedge_action = wedge_action
        self._report_error: Optional[Callable[[Exception], None]] = None
        self._lock = threading.Lock()
        self._eject_reason: Optional[str] = None
        self._ejection_recorded = False
        self._refusal_counted = False
        self._accused: Optional[str] = None
        self.state = STATE_HEALTHY
        self._labels = {
            "replica_id": replica_id,
            "group_rank": str(self.group_rank),
        }
        self._set_state(STATE_HEALTHY)

    # -- wiring -------------------------------------------------------------

    def bind(
        self,
        trace: Optional["tracing.TraceJournal"] = None,
        report_error: Optional[Callable[[Exception], None]] = None,
        min_replica_size: Optional[int] = None,
    ) -> None:
        if trace is not None:
            self._trace = trace
        if report_error is not None:
            self._report_error = report_error
        if min_replica_size is not None:
            self.min_replica_size = int(min_replica_size)

    def _journal(self) -> "tracing.TraceJournal":
        return self._trace or tracing.current()

    def _set_state(self, state: int) -> None:
        self.state = state
        metrics.set_gauge("tpuft_health_state", state, **self._labels)

    # -- quorum-side plumbing ------------------------------------------------

    def on_quorum(self, quorum: Any) -> None:
        """Peer discovery off the quorum view the manager already holds:
        participant stable ids + the quorum's shared rendezvous store as
        the snapshot board. Best-effort everywhere."""
        try:
            q = getattr(quorum, "quorum", None)
            if q is not None:
                self._peer_ids = sorted(
                    {
                        str(member.replica_id).split(":", 1)[0]
                        for member in q.participants
                    }
                    - {self.replica_id}
                )
            addr = getattr(quorum, "store_address", "") or ""
            if addr and addr != self._board_addr and not self._board_pinned:
                from torchft_tpu.parallel.store import create_store_client

                board = create_store_client(addr, connect_timeout=2.0)
                old = self._board
                self._board, self._board_addr = board, addr
                if old is not None and hasattr(old, "close"):
                    try:
                        old.close()
                    except Exception:  # noqa: BLE001
                        pass
        except Exception:  # noqa: BLE001 — discovery is best-effort
            logger.debug("health peer discovery failed", exc_info=True)

    def set_peers(self, peer_ids: List[str], board: Any) -> None:
        """Direct wiring for the bench / tests (no quorum object)."""
        self._peer_ids = [p for p in peer_ids if p != self.replica_id]
        self._board = board
        self._board_pinned = True

    def _push_snapshot(self) -> None:
        if self._board is None:
            return
        try:
            snap = self.scorer.snapshot()
            snap["state"] = self.state
            if self._accused:
                snap["accused"] = self._accused
            self._board.set(
                f"{BOARD_PREFIX}/{self.replica_id}", json.dumps(snap).encode()
            )
        except Exception:  # noqa: BLE001 — the board must not wound a step
            logger.debug("health snapshot push failed", exc_info=True)

    def _pull_peers(self) -> None:
        if self._board is None:
            return
        for rid in self._peer_ids:
            try:
                raw = self._board.get(
                    f"{BOARD_PREFIX}/{rid}", timeout=1.0, wait=False
                )
                if raw is None:
                    continue
                snap = json.loads(
                    raw.decode() if isinstance(raw, (bytes, bytearray)) else raw
                )
                self.scorer.note_peer(
                    rid, snap.get("phases") or {}, ts=snap.get("ts")
                )
            except Exception:  # noqa: BLE001
                continue
        metrics.set_gauge(
            "tpuft_health_peer_snapshots",
            len(self.scorer.fresh_peers()),
            **self._labels,
        )

    # -- the step-boundary loop ---------------------------------------------

    def on_step(
        self, step: int, committed: bool = True, participants: Optional[int] = None
    ) -> None:
        """The per-step hook (commit-resolution tail). Cheap and
        exception-free by contract: watchdog beat, rollup ingest, board
        push/pull (rate-limited), one scoring window, verdict latching.
        Actuation (the raise) happens later, at ``start_quorum``."""
        try:
            self._on_step(step, committed, participants)
        except Exception:  # noqa: BLE001 — observability must not wound
            logger.exception("health on_step failed (ignored)")

    def _on_step(
        self, step: int, committed: bool, participants: Optional[int]
    ) -> None:
        assert self._watchdog is not None
        self._watchdog.beat()
        if participants is not None:
            self._participants = int(participants)
        journal = self._journal()
        self.scorer.ingest_rollup(journal.phase_rollup())
        now = self._wall()
        push_due = now - self._last_push >= self._push_interval
        if push_due:
            self._last_push = now
            self._pull_peers()
        verdict = self.scorer.evaluate()
        for phase, ratio in verdict["ratios"].items():
            metrics.set_gauge(
                "tpuft_health_phase_ratio", ratio, phase=phase, **self._labels
            )
        self._update_accusation()
        latched = False
        with self._lock:
            latched = self._eject_reason is not None
        if not latched:
            if verdict["degraded"]:
                self._latch_degraded(step, verdict)
            elif self.state in (STATE_HEALTHY, STATE_SUSPECT, STATE_DEGRADED):
                if verdict["streak"] > 0:
                    self._set_state(STATE_SUSPECT)
                else:
                    self._set_state(STATE_HEALTHY)
                    self._refusal_counted = False
        if push_due:
            # Pushed AFTER the window so peers (and fleet_status) see the
            # freshest EWMAs/state/accusation, not last window's.
            self._push_snapshot()

    def _update_accusation(self) -> None:
        accusation = self.scorer.accuse()
        accused = accusation[0] if accusation else None
        if accused == self.replica_id:
            accused = None  # self-blame rides the verdict plane instead
        if accused != self._accused:
            if self._accused is not None:
                metrics.set_gauge(
                    "tpuft_health_accuse", 0, accused=self._accused, **self._labels
                )
            if accused is not None:
                metrics.set_gauge(
                    "tpuft_health_accuse", 1, accused=accused, **self._labels
                )
                metrics.inc("tpuft_health_accusations_total", **self._labels)
                self._journal().record(
                    "health_accuse",
                    accused=accused,
                    gap_s=round(accusation[1], 4) if accusation else 0.0,
                )
            self._accused = accused

    def _latch_degraded(self, step: int, verdict: Dict[str, Any]) -> None:
        """A degraded verdict: eject unless that would drop the quorum
        below min_replica_size — then refuse (counted once per latch)
        and keep training degraded; re-checked every window so a later
        join unlocks the ejection."""
        if self._participants and self._participants - 1 < self.min_replica_size:
            self._set_state(STATE_DEGRADED)
            if not self._refusal_counted:
                self._refusal_counted = True
                metrics.inc(
                    "tpuft_health_ejections_refused_total", **self._labels
                )
                self._journal().record(
                    "health_ejection_refused",
                    participants=self._participants,
                    min_replica=self.min_replica_size,
                    ratios=json.dumps(verdict["ratios"]),
                )
                logger.warning(
                    "degraded verdict for %s REFUSED: ejecting would drop "
                    "participants %d below min_replica_size %d; training "
                    "continues degraded",
                    self.replica_id, self._participants, self.min_replica_size,
                )
            return
        metrics.inc("tpuft_health_verdicts_total", **self._labels)
        self._set_state(STATE_DEGRADED)
        reason = (
            f"self-verdict: phases {verdict['ratios']} beyond "
            f"{self.scorer.threshold}x the fleet median for "
            f"{verdict['streak']} consecutive windows"
        )
        self._journal().record(
            "health_verdict",
            step=step,
            streak=verdict["streak"],
            ratios=json.dumps(verdict["ratios"]),
            peers=verdict["peers"],
        )
        with self._lock:
            self._eject_reason = reason

    # -- wedge path ----------------------------------------------------------

    def _on_wedge(self, elapsed: float, deadline: float) -> None:
        """Watchdog thread: the train thread is presumed stuck, so this
        path must complete the accounting itself (record, report, dump)
        and then escalate. Default escalation is SIGTERM to our own
        process (``TPUFT_HEALTH_WEDGE_ACTION=term``) — the supervisor
        restarts us and the quarantine gate re-probes; ``flag`` only
        latches the ejection for the next step boundary (thread drills,
        and fleets whose wedges are known to resolve)."""
        reason = (
            f"step-progress watchdog: no step in {elapsed:.1f}s "
            f"(deadline {deadline:.1f}s from the replica's own cadence)"
        )
        metrics.inc("tpuft_health_wedge_trips_total", **self._labels)
        journal = self._journal()
        journal.record(
            "health_wedge", elapsed_s=round(elapsed, 3),
            deadline_s=round(deadline, 3),
        )
        tracing.open_incident(
            "health_wedge", journal.step, journal.quorum_id,
            journal=journal, reason=reason,
        )
        self.gate.record_ejection(reason)
        metrics.inc("tpuft_health_ejections_total", **self._labels)
        self._set_state(STATE_QUARANTINED)
        with self._lock:
            self._eject_reason = reason
            # The accounting above already happened; the (possibly
            # unreachable) train thread's note_ejected must not repeat it.
            self._ejection_recorded = True
        if self._report_error is not None:
            try:
                self._report_error(DegradedReplicaError(reason))
            except Exception:  # noqa: BLE001
                pass
        # Injected wedges clear like a process restart would; a REAL
        # wedge needs the hard escalation below to unpark the replica.
        clear_injected(self.replica_id)
        action = self._wedge_action
        if action is not None:
            try:
                action()
            except Exception:  # noqa: BLE001
                logger.exception("wedge escalation callback failed")
            return
        if os.environ.get(ENV_WEDGE_ACTION, "term") == "term":
            logger.error(
                "wedged replica %s: SIGTERM to self for supervisor restart "
                "(%s)", self.replica_id, reason,
            )
            os.kill(os.getpid(), signal.SIGTERM)

    # -- actuation (manager's start_quorum) -----------------------------------

    def should_eject(self) -> Optional[str]:
        with self._lock:
            return self._eject_reason

    def note_ejected(self, reason: str) -> None:
        """Called by the manager right before the DegradedReplicaError
        raise: persist the ejection for the restarted process's gate,
        count it, stamp the incident, and clear this replica's injected
        gray faults (the thread-drill analogue of the process dying).
        Idempotent with the wedge path's own accounting."""
        with self._lock:
            already = self._ejection_recorded
            self._ejection_recorded = False
        if not already:
            self.gate.record_ejection(reason)
            metrics.inc("tpuft_health_ejections_total", **self._labels)
        journal = self._journal()
        journal.record("health_ejection", reason=reason)
        tracing.open_incident(
            "health_ejection", journal.step, journal.quorum_id,
            journal=journal, reason=reason,
        )
        self._set_state(STATE_QUARANTINED)
        clear_injected(self.replica_id)

    def serve_quarantine_if_pending(self) -> Optional[Dict[str, Any]]:
        """The startup gate (Manager construction / bench rejoin): a
        replica with a recent ejection on file proves itself healthy —
        probe with backoff, park if crash-looping — before it may rejoin
        the fleet. Returns the served record, or None when clean."""
        if not self.gate.pending():
            return None
        self._set_state(
            STATE_PARKED if self.gate.parked_until() > 0 else STATE_QUARANTINED
        )
        record = self.gate.serve(trace=self._journal())
        self._set_state(STATE_HEALTHY)
        with self._lock:
            self._eject_reason = None
            self._ejection_recorded = False
        # Re-admission scores fresh, like the restarted process it
        # models: evidence gathered while degraded/wedged (e.g. the
        # blocked sync's huge sample) must not re-verdict a healthy
        # comeback.
        self.scorer.ewma.clear()
        self.scorer.counts.clear()
        self.scorer.streak = 0
        self._refusal_counted = False
        self._journal().record("health_rejoin", **record)
        return record

    def stop(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
