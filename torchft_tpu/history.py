"""Versioned weight history: ONE step-labeled ring of committed snapshots.

The repo previously held "recent committed state" in three independent
stores with different lifetimes: the pipelined-commit rollback ring
(optim._PendingStep slots, dropped at resolution), the serving
publisher's staged version (checkpointing/http_transport.py, replaced on
every stage), and the donor/serve-child staging area (one epoch,
replaced on restage). The seams between them were the two documented
weaknesses: a deep-window donor could only serve its DRAINED step (the
first post-drain heal round failed cleanly and retried), and a retracted
published version left readers with no sanctioned fallback. This module
unifies them:

- :class:`WeightHistory` — the manager-side ring of committed STATE
  REFS, keyed by step. Entries are per-registered-key immutable pytrees
  (jax/numpy leaves are never mutated in place — holding a reference IS
  a snapshot, exactly the argument ``WeightPublisher.publish`` already
  relies on). The pipelined optimizer promotes each slot's committed
  state here at resolution instead of dropping it, so a donor asked for
  ``quorum.max_step`` can stage that exact committed step even when its
  live window drained past it — the PR-9 "fail cleanly and retry"
  envelope becomes an immediate serve. The ring only ever ingests
  COMMITTED state (promotion happens at commit resolution; rollbacks
  retract), so analyzer rule R7's speculation discipline is untouched.

- :class:`StagedVersionStore` — the serving-side ring of fully staged
  versions in the exact PR-4 heal format (per-chunk CRCs, sha256 digest,
  era tag): the publisher's transport keeps the last K staged versions
  servable so ``/serving/version/{step}`` and ``latest-1`` reads hit
  real bytes, retraction can converge readers to V-1, and a lagging
  relay/rejoiner delta-chains across resident manifests instead of
  paying a full pull. In ``TPUFT_HEAL_SERVE_MODE=child`` the resident
  versions live as /dev/shm epoch directories owned by the serve child
  (serve_child.py keeps the same budgeted ring of epochs).

Budget: K adapts to ``TPUFT_HISTORY_BYTES`` (total resident payload
bytes; the same accounting as ``tpuft_pipeline_snapshot_bytes`` — one
full (params, opt_state) copy per version is THE memory cost) and is
capped by ``TPUFT_HISTORY_MAX_VERSIONS``. The newest committed version
is never evicted; ``K=1`` degrades bit-for-bit to the pre-history
behavior (only the live committed state exists). Defaults: the manager
ring sizes itself off the commit-pipeline depth (depth+1 — the versions
the rollback ring already held), the serving store keeps
:data:`DEFAULT_SERVING_VERSIONS`.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from torchft_tpu import metrics

__all__ = [
    "WeightHistory",
    "StagedVersionStore",
    "ENV_HISTORY_BYTES",
    "ENV_HISTORY_MAX_VERSIONS",
    "history_bytes_budget",
    "history_max_versions",
    "DEFAULT_SERVING_VERSIONS",
]

ENV_HISTORY_BYTES = "TPUFT_HISTORY_BYTES"
ENV_HISTORY_MAX_VERSIONS = "TPUFT_HISTORY_MAX_VERSIONS"

# Serving-side default ring width: latest + latest-1 for rollback/canary
# plus two more for pinned readers and delta chains. Small on purpose —
# every resident version is a full payload copy.
DEFAULT_SERVING_VERSIONS = 4


def history_bytes_budget(default: Optional[int] = None) -> Optional[int]:
    """Total resident-bytes budget for a history ring
    (``$TPUFT_HISTORY_BYTES``; unset/<=0 = count-bounded only)."""
    raw = os.environ.get(ENV_HISTORY_BYTES)
    if raw is None:
        return default
    try:
        value = int(float(raw))
    except ValueError:
        return default
    return value if value > 0 else None


def history_max_versions(default: int) -> int:
    """Resident-version cap for a history ring
    (``$TPUFT_HISTORY_MAX_VERSIONS``; >= 1 — the newest is never
    evicted)."""
    raw = os.environ.get(ENV_HISTORY_MAX_VERSIONS)
    if raw is None:
        return max(1, default)
    try:
        return max(1, int(raw))
    except ValueError:
        return max(1, default)


class _StateEntry:
    """One committed step's state refs: per-registered-key pytrees plus
    the manager accounting that makes the entry a complete, honestly
    labeled checkpoint (``batches_committed`` at that step)."""

    __slots__ = ("step", "quorum_id", "states", "nbytes", "batches_committed")

    def __init__(self, step: int) -> None:
        self.step = step
        self.quorum_id: Optional[int] = None
        self.states: Dict[str, Any] = {}
        self.nbytes = 0
        self.batches_committed: Optional[int] = None


class WeightHistory:
    """Byte-budgeted, step-labeled ring of committed state references.

    Thread-safe: promotion lands from the train loop, the commit pool,
    and the quorum thread (drain hooks); lookups come from the quorum
    thread's donor-staging path. All entries are committed-only BY
    CONSTRUCTION — callers promote at commit resolution, never from a
    live speculative window — and a rollback-unwind retracts every entry
    newer than the surviving committed step.
    """

    def __init__(
        self,
        max_versions: Optional[int] = None,
        max_bytes: Optional[int] = None,
        ring: str = "state",
    ) -> None:
        self._max_versions = history_max_versions(
            max_versions if max_versions is not None else 1
        )
        self._max_bytes = history_bytes_budget(max_bytes)
        self._ring = ring
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, _StateEntry]" = OrderedDict()

    # -- ingestion ---------------------------------------------------------

    def note_state(
        self,
        key: str,
        step: int,
        state: Any,
        nbytes: int = 0,
        quorum_id: Optional[int] = None,
    ) -> None:
        """Promotes one registered key's committed state at ``step``.
        ``state`` must be an immutable pytree (the committed refs); the
        caller supplies its resident-byte estimate (the
        ``tpuft_pipeline_snapshot_bytes`` accounting)."""
        if step <= 0:
            return  # step 0 is the init_sync mosaic: per-rank, never served
        with self._lock:
            entry = self._entries.get(step)
            if entry is None:
                entry = _StateEntry(step)
                self._entries[step] = entry
                # Keep step order even if promotions race slightly out of
                # order across threads (drain vs train loop).
                if list(self._entries) != sorted(self._entries):
                    self._entries = OrderedDict(
                        sorted(self._entries.items())
                    )
            if key not in entry.states:  # idempotent: first promotion wins
                entry.states[key] = state
                entry.nbytes += max(0, int(nbytes))
            if quorum_id is not None:
                entry.quorum_id = quorum_id
            metrics.inc("tpuft_history_promotions_total")
            self._evict_locked()
            self._publish_gauges_locked()

    def note_accounting(self, step: int, batches_committed: int) -> None:
        """Records the manager accounting at a committed step (cheap ints
        — safe on the commit tail, unlike a state sample). Creates the
        entry when it is first: the commit tail runs BEFORE the state
        owner's promotion, and an entry is servable only once both
        halves landed."""
        if step <= 0:
            return
        with self._lock:
            entry = self._entries.get(step)
            if entry is None:
                entry = _StateEntry(step)
                self._entries[step] = entry
                if list(self._entries) != sorted(self._entries):
                    self._entries = OrderedDict(sorted(self._entries.items()))
                self._evict_locked()
            entry.batches_committed = int(batches_committed)

    # -- lookup ------------------------------------------------------------

    def state_dict_at(
        self, step: int, required_keys: Set[str]
    ) -> Optional[Dict[str, Any]]:
        """The full manager-shaped state dict for committed ``step`` —
        ``{"user": {key: state}, "tpuft": {step, batches_committed}}`` —
        or None when the ring cannot serve it exactly (step evicted /
        never promoted, a registered key missing, or accounting absent).
        A miss means the caller falls back to staging its drained step;
        it can never mean serving mislabeled or partial state."""
        with self._lock:
            entry = self._entries.get(step)
            if entry is None:
                return None
            if required_keys - set(entry.states):
                return None
            if entry.batches_committed is None:
                return None
            return {
                "user": {k: entry.states[k] for k in required_keys},
                "tpuft": {
                    "step": step,
                    "batches_committed": entry.batches_committed,
                },
            }

    def resident_steps(self) -> List[int]:
        with self._lock:
            return list(self._entries)

    def nbytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- retraction / lifecycle --------------------------------------------

    def retract_newer(self, committed_step: int) -> int:
        """Drops every entry newer than the surviving committed step (the
        rollback-unwind twin of the publisher's due-mark retraction);
        returns how many were dropped. Promotion is commit-resolution-
        gated so this is belt-and-braces — refused steps were never
        promoted — but it keeps the ring provably on the committed
        trajectory even across the phantom-commit envelope."""
        with self._lock:
            doomed = [s for s in self._entries if s > committed_step]
            for s in doomed:
                del self._entries[s]
            if doomed:
                self._publish_gauges_locked()
            return len(doomed)

    def clear(self) -> None:
        """Forget everything (a user checkpoint restore rewrote the step
        counter: old step labels no longer describe this trajectory)."""
        with self._lock:
            self._entries.clear()
            self._publish_gauges_locked()

    # -- internals ---------------------------------------------------------

    def _evict_locked(self) -> None:
        def over_budget() -> bool:
            if len(self._entries) > self._max_versions:
                return True
            if self._max_bytes is not None and len(self._entries) > 1:
                total = sum(e.nbytes for e in self._entries.values())
                return total > self._max_bytes
            return False

        while len(self._entries) > 1 and over_budget():
            self._entries.popitem(last=False)  # oldest; newest never goes
            metrics.inc("tpuft_history_evictions_total")

    def _publish_gauges_locked(self) -> None:
        metrics.set_gauge(
            "tpuft_history_versions", len(self._entries), ring=self._ring
        )
        metrics.set_gauge(
            "tpuft_history_bytes",
            sum(e.nbytes for e in self._entries.values()),
            ring=self._ring,
        )


class StagedVersionStore:
    """Ring of fully STAGED versions (opaque payload handles — the inline
    transport's ``_Staged`` objects, or child-mode epoch records): the
    serving plane's resident history. Same budget/eviction semantics as
    :class:`WeightHistory`; an ``on_evict`` callback releases payload
    resources (child mode deletes the epoch directory). Retraction
    removes a version and remembers its step so later reads answer
    "retracted" (410) instead of "never existed" (404)."""

    def __init__(
        self,
        max_versions: Optional[int] = None,
        max_bytes: Optional[int] = None,
        on_evict: Optional[Callable[[int, Any], None]] = None,
        ring: str = "staged",
    ) -> None:
        self._max_versions = history_max_versions(
            max_versions if max_versions is not None else DEFAULT_SERVING_VERSIONS
        )
        self._max_bytes = history_bytes_budget(max_bytes)
        self._on_evict = on_evict
        self._ring = ring
        self._lock = threading.Lock()
        self._versions: "OrderedDict[int, Tuple[Any, int]]" = OrderedDict()
        self._retracted: Set[int] = set()

    @property
    def max_versions(self) -> int:
        return self._max_versions

    def put(self, step: int, payload: Any, nbytes: int) -> None:
        evicted: List[Tuple[int, Any]] = []
        with self._lock:
            self._versions[step] = (payload, max(0, int(nbytes)))
            if list(self._versions) != sorted(self._versions):
                self._versions = OrderedDict(sorted(self._versions.items()))
            self._retracted.discard(step)
            metrics.inc("tpuft_history_promotions_total")
            while len(self._versions) > 1 and self._over_budget_locked():
                old_step, (old_payload, _n) = self._versions.popitem(last=False)
                metrics.inc("tpuft_history_evictions_total")
                evicted.append((old_step, old_payload))
            self._publish_gauges_locked()
        for old_step, old_payload in evicted:
            self._release(old_step, old_payload)

    def get(self, step: int) -> Optional[Any]:
        with self._lock:
            held = self._versions.get(step)
            return held[0] if held is not None else None

    def steps(self) -> List[int]:
        with self._lock:
            return list(self._versions)

    def latest_steps(self, n: int) -> List[int]:
        """The newest ``n`` resident steps, newest first."""
        with self._lock:
            return list(self._versions)[-n:][::-1]

    def is_retracted(self, step: int) -> bool:
        with self._lock:
            return step in self._retracted

    def drop(self, step: int, retracted: bool = False) -> bool:
        """Removes one resident version (``retracted=True`` remembers the
        step so reads answer 410 — the operator rollback path)."""
        with self._lock:
            held = self._versions.pop(step, None)
            if retracted:
                self._retracted.add(step)
            if held is None:
                return False
            self._publish_gauges_locked()
        self._release(step, held[0])
        return True

    def drop_newer(self, step: int, retracted: bool = True) -> List[int]:
        """Removes every resident version newer than ``step`` (retraction
        convergence: after retracting V the ring must hold nothing past
        V-1, never a torn mix); returns the dropped steps."""
        with self._lock:
            doomed = [(s, self._versions.pop(s)) for s in list(self._versions) if s > step]
            if retracted:
                self._retracted.update(s for s, _ in doomed)
            if doomed:
                self._publish_gauges_locked()
        for s, (payload, _n) in doomed:
            self._release(s, payload)
        return [s for s, _ in doomed]

    def clear(self) -> None:
        with self._lock:
            doomed = list(self._versions.items())
            self._versions.clear()
            self._retracted.clear()
            self._publish_gauges_locked()
        for s, (payload, _n) in doomed:
            self._release(s, payload)

    def __len__(self) -> int:
        with self._lock:
            return len(self._versions)

    def _release(self, step: int, payload: Any) -> None:
        if self._on_evict is not None:
            try:
                self._on_evict(step, payload)
            except Exception:  # noqa: BLE001 — eviction must never wound serving
                pass

    def _over_budget_locked(self) -> bool:
        if len(self._versions) > self._max_versions:
            return True
        if self._max_bytes is not None:
            total = sum(n for _p, n in self._versions.values())
            return total > self._max_bytes
        return False

    def _publish_gauges_locked(self) -> None:
        metrics.set_gauge(
            "tpuft_history_versions", len(self._versions), ring=self._ring
        )
        metrics.set_gauge(
            "tpuft_history_bytes",
            sum(n for _p, n in self._versions.values()),
            ring=self._ring,
        )
