"""Job launcher: replica-group supervision for one or many hosts.

Role-equivalent of the reference's launch tooling — ``torchft/torchx.py``
(per-replica-group roles with REPLICA_GROUP_ID / NUM_REPLICA_GROUPS /
lighthouse env wiring) and ``examples/slurm/runner.py`` (a supervision loop
that relaunches dead replica groups).

    python -m torchft_tpu.launch --num-replica-groups 4 -- \
        python examples/train_ddp.py --steps 100

Each replica group becomes a supervised subprocess with:
  REPLICA_GROUP_ID, NUM_REPLICA_GROUPS, TPUFT_LIGHTHOUSE
plus any TPUFT_* timeouts passed through. Dead groups are relaunched every
``--relaunch-interval`` seconds up to ``--max-restarts``, mirroring the
torchelastic max_restarts contract the reference delegates to.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from torchft_tpu.coordination import LighthouseServer

__all__ = ["supervise", "main"]


def supervise(
    command: List[str],
    num_replica_groups: int,
    lighthouse_addr: Optional[str] = None,
    relaunch_interval: float = 10.0,
    max_restarts: int = 100,
    extra_env: Optional[Dict[str, str]] = None,
    group_world_size: int = 1,
    store_port_base: int = 29600,
    jax_coordinator_port_base: int = 0,
) -> int:
    """Runs ``command`` for each (group, rank) cell, relaunching dead
    groups. With ``group_world_size > 1`` every rank of a group shares
    GROUP_WORLD_SIZE/TPUFT_STORE_ADDR (group rank 0 binds the store on
    ``store_port_base + group``); a death of any rank restarts the whole
    group, matching the per-group restart unit of the reference's
    torchelastic deployment. Returns 0 when every group exits cleanly."""
    if group_world_size < 1:
        raise ValueError(f"group_world_size must be >= 1, got {group_world_size}")
    if jax_coordinator_port_base and group_world_size == 1:
        raise ValueError(
            "--jax-coordinator-port-base requires --group-world-size > 1 "
            "(a one-process group has nothing to cluster)"
        )
    own_lighthouse: Optional[LighthouseServer] = None
    if lighthouse_addr is None:
        own_lighthouse = LighthouseServer(
            min_replicas=1, join_timeout_ms=10000, heartbeat_timeout_ms=5000
        )
        lighthouse_addr = own_lighthouse.address()
        print(f"[launch] embedded lighthouse at {lighthouse_addr}", flush=True)

    import socket as _socket

    hostname = _socket.gethostname()

    def spawn_group(group: int) -> List[subprocess.Popen]:
        procs = []
        store_addr = f"{hostname}:{store_port_base + group}"
        for rank in range(group_world_size):
            env = {
                **os.environ,
                **(extra_env or {}),
                "REPLICA_GROUP_ID": str(group),
                "NUM_REPLICA_GROUPS": str(num_replica_groups),
                "GROUP_RANK": str(rank),
                "GROUP_WORLD_SIZE": str(group_world_size),
                "TPUFT_LIGHTHOUSE": lighthouse_addr,
            }
            if group_world_size > 1:
                env["TPUFT_STORE_ADDR"] = store_addr
                if jax_coordinator_port_base:
                    env["TPUFT_JAX_COORDINATOR"] = (
                        f"{hostname}:{jax_coordinator_port_base + group}"
                    )
            print(
                f"[launch] starting group {group} rank {rank}: {' '.join(command)}",
                flush=True,
            )
            procs.append(subprocess.Popen(command, env=env))
        return procs

    groups = {g: spawn_group(g) for g in range(num_replica_groups)}
    restarts = {g: 0 for g in range(num_replica_groups)}
    done: Dict[int, int] = {}
    try:
        while len(done) < num_replica_groups:
            time.sleep(min(relaunch_interval, 1.0))
            for group, procs in list(groups.items()):
                if group in done:
                    continue
                codes = [p.poll() for p in procs]
                if all(code == 0 for code in codes):
                    print(f"[launch] group {group} finished", flush=True)
                    done[group] = 0
                    continue
                failed = [code for code in codes if code not in (None, 0)]
                if not failed:
                    continue
                # Any dead rank restarts the whole group. Shared deadline so
                # a wedged multi-rank group can't stall supervision of the
                # others; after SIGKILL, reap each child so its sockets (the
                # fixed store port) are released before the respawn.
                for p in procs:
                    if p.poll() is None:
                        p.send_signal(signal.SIGTERM)
                term_deadline = time.monotonic() + 5
                for p in procs:
                    try:
                        p.wait(timeout=max(0.1, term_deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                if restarts[group] < max_restarts:
                    restarts[group] += 1
                    print(
                        f"[launch] group {group} died (exit {failed[0]}); "
                        f"relaunch {restarts[group]}/{max_restarts} "
                        f"in {relaunch_interval}s",
                        flush=True,
                    )
                    time.sleep(relaunch_interval)
                    groups[group] = spawn_group(group)
                else:
                    print(
                        f"[launch] group {group} exhausted restarts (exit {failed[0]})",
                        flush=True,
                    )
                    done[group] = failed[0]
        return 0 if all(code == 0 for code in done.values()) else 1
    finally:
        all_procs = [p for procs in groups.values() for p in procs]
        for proc in all_procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5
        for proc in all_procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        if own_lighthouse is not None:
            own_lighthouse.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-replica-groups", type=int, required=True)
    parser.add_argument("--lighthouse", default=os.environ.get("TPUFT_LIGHTHOUSE"))
    parser.add_argument("--relaunch-interval", type=float, default=10.0)
    parser.add_argument("--max-restarts", type=int, default=100)
    parser.add_argument("--group-world-size", type=int, default=1)
    parser.add_argument("--store-port-base", type=int, default=29600)
    parser.add_argument(
        "--jax-coordinator-port-base",
        type=int,
        default=0,
        help="when set, each group's ranks form one jax.distributed cluster "
        "(coordinator on this port + group id)",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER, help="-- cmd args...")
    args = parser.parse_args()
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("missing command (after --)")
    sys.exit(
        supervise(
            command,
            num_replica_groups=args.num_replica_groups,
            lighthouse_addr=args.lighthouse,
            relaunch_interval=args.relaunch_interval,
            max_restarts=args.max_restarts,
            group_world_size=args.group_world_size,
            store_port_base=args.store_port_base,
            jax_coordinator_port_base=args.jax_coordinator_port_base,
        )
    )


if __name__ == "__main__":
    main()
