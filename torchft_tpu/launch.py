"""Job launcher: replica-group supervision for one or many hosts.

Role-equivalent of the reference's launch tooling — ``torchft/torchx.py``
(per-replica-group roles with REPLICA_GROUP_ID / NUM_REPLICA_GROUPS /
lighthouse env wiring) and ``examples/slurm/runner.py`` (a supervision loop
that relaunches dead replica groups).

    python -m torchft_tpu.launch --num-replica-groups 4 -- \
        python examples/train_ddp.py --steps 100

Each replica group becomes a supervised subprocess with:
  REPLICA_GROUP_ID, NUM_REPLICA_GROUPS, TPUFT_LIGHTHOUSE
plus any TPUFT_* timeouts passed through. Dead groups are relaunched every
``--relaunch-interval`` seconds up to ``--max-restarts``, mirroring the
torchelastic max_restarts contract the reference delegates to.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from torchft_tpu.coordination import LighthouseServer

__all__ = ["supervise", "main"]


def supervise(
    command: List[str],
    num_replica_groups: int,
    lighthouse_addr: Optional[str] = None,
    relaunch_interval: float = 10.0,
    max_restarts: int = 100,
    extra_env: Optional[Dict[str, str]] = None,
) -> int:
    """Runs ``command`` once per replica group, relaunching dead groups.
    Returns 0 when every group has exited cleanly."""
    own_lighthouse: Optional[LighthouseServer] = None
    if lighthouse_addr is None:
        own_lighthouse = LighthouseServer(
            min_replicas=1, join_timeout_ms=10000, heartbeat_timeout_ms=5000
        )
        lighthouse_addr = own_lighthouse.address()
        print(f"[launch] embedded lighthouse at {lighthouse_addr}", flush=True)

    def spawn(group: int) -> subprocess.Popen:
        env = {
            **os.environ,
            **(extra_env or {}),
            "REPLICA_GROUP_ID": str(group),
            "NUM_REPLICA_GROUPS": str(num_replica_groups),
            "TPUFT_LIGHTHOUSE": lighthouse_addr,
        }
        print(f"[launch] starting replica group {group}: {' '.join(command)}", flush=True)
        return subprocess.Popen(command, env=env)

    procs = {g: spawn(g) for g in range(num_replica_groups)}
    restarts = {g: 0 for g in range(num_replica_groups)}
    done: Dict[int, int] = {}
    try:
        while len(done) < num_replica_groups:
            time.sleep(min(relaunch_interval, 1.0))
            for group, proc in list(procs.items()):
                if group in done:
                    continue
                code = proc.poll()
                if code is None:
                    continue
                if code == 0:
                    print(f"[launch] group {group} finished", flush=True)
                    done[group] = 0
                elif restarts[group] < max_restarts:
                    restarts[group] += 1
                    print(
                        f"[launch] group {group} died (exit {code}); "
                        f"relaunch {restarts[group]}/{max_restarts} "
                        f"in {relaunch_interval}s",
                        flush=True,
                    )
                    time.sleep(relaunch_interval)
                    procs[group] = spawn(group)
                else:
                    print(
                        f"[launch] group {group} exhausted restarts (exit {code})",
                        flush=True,
                    )
                    done[group] = code
        return 0 if all(code == 0 for code in done.values()) else 1
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5
        for proc in procs.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        if own_lighthouse is not None:
            own_lighthouse.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-replica-groups", type=int, required=True)
    parser.add_argument("--lighthouse", default=os.environ.get("TPUFT_LIGHTHOUSE"))
    parser.add_argument("--relaunch-interval", type=float, default=10.0)
    parser.add_argument("--max-restarts", type=int, default=100)
    parser.add_argument("command", nargs=argparse.REMAINDER, help="-- cmd args...")
    args = parser.parse_args()
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("missing command (after --)")
    sys.exit(
        supervise(
            command,
            num_replica_groups=args.num_replica_groups,
            lighthouse_addr=args.lighthouse,
            relaunch_interval=args.relaunch_interval,
            max_restarts=args.max_restarts,
        )
    )


if __name__ == "__main__":
    main()
