"""Job launcher: replica-group supervision for one or many hosts.

Role-equivalent of the reference's launch tooling — ``torchft/torchx.py``
(per-replica-group roles with REPLICA_GROUP_ID / NUM_REPLICA_GROUPS /
lighthouse env wiring) and ``examples/slurm/runner.py`` (a supervision loop
that relaunches dead replica groups).

    python -m torchft_tpu.launch --num-replica-groups 4 -- \
        python examples/train_ddp.py --steps 100

Each replica group becomes a supervised subprocess with:
  REPLICA_GROUP_ID, NUM_REPLICA_GROUPS, TPUFT_LIGHTHOUSE
plus any TPUFT_* timeouts passed through. Dead groups are relaunched with
**exponential backoff**: the delay doubles per recent rapid death (deaths
within ``_backoff_window`` seconds of each other — a genuinely
crash-looping group, not chaos kills minutes apart), capped at
``--relaunch-backoff-max``, so a hot-looping group cannot spin the host.
Restart exhaustion is **windowed**, not lifetime: ``--max-restarts``
restarts inside the sliding ``--restart-window`` seconds gives up on the
group (the torchelastic max_restarts contract, hardened for long-running
jobs where a lifetime counter eventually strands a healthy fleet over
unrelated faults spread across days).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from torchft_tpu.coordination import LighthouseServer

__all__ = ["supervise", "main", "relaunch_delay", "prune_restart_window"]


def relaunch_delay(
    base: float, recent_rapid_deaths: int, cap: float
) -> float:
    """The relaunch backoff schedule (pure function, unit-pinned):
    ``min(base * 2^n, cap)`` where ``n`` counts RECENT rapid deaths —
    deaths inside the short backoff window, i.e. evidence of a hot
    crash loop. Chaos kills minutes apart keep ``n`` at 0 and relaunch
    at the base interval; an instant-exit loop escalates geometrically
    to the cap."""
    return min(base * (2.0 ** max(recent_rapid_deaths, 0)), max(cap, base))


def prune_restart_window(
    restarts: List[float], now: float, window: float
) -> List[float]:
    """Sliding-window restart accounting (pure function, unit-pinned):
    keeps only restart timestamps within ``window`` seconds of ``now``.
    ``window <= 0`` disables pruning (lifetime semantics)."""
    if window <= 0:
        return list(restarts)
    return [t for t in restarts if now - t <= window]


def supervise(
    command: List[str],
    num_replica_groups: int,
    lighthouse_addr: Optional[str] = None,
    relaunch_interval: float = 10.0,
    max_restarts: int = 100,
    extra_env: Optional[Dict[str, str]] = None,
    group_world_size: int = 1,
    store_port_base: int = 29600,
    jax_coordinator_port_base: int = 0,
    restart_window: float = 600.0,
    relaunch_backoff_max: Optional[float] = None,
) -> int:
    """Runs ``command`` for each (group, rank) cell, relaunching dead
    groups. With ``group_world_size > 1`` every rank of a group shares
    GROUP_WORLD_SIZE/TPUFT_STORE_ADDR (group rank 0 binds the store on
    ``store_port_base + group``); a death of any rank restarts the whole
    group, matching the per-group restart unit of the reference's
    torchelastic deployment. Returns 0 when every group exits cleanly.

    Crash-loop hardening: the relaunch delay doubles per rapid death
    (:func:`relaunch_delay`, capped at ``relaunch_backoff_max``, default
    ``max(8 x relaunch_interval, relaunch_interval)``), and a group is
    given up only after ``max_restarts`` restarts inside the sliding
    ``restart_window`` seconds (:func:`prune_restart_window`;
    ``restart_window <= 0`` restores the legacy lifetime count)."""
    if group_world_size < 1:
        raise ValueError(f"group_world_size must be >= 1, got {group_world_size}")
    if jax_coordinator_port_base and group_world_size == 1:
        raise ValueError(
            "--jax-coordinator-port-base requires --group-world-size > 1 "
            "(a one-process group has nothing to cluster)"
        )
    own_lighthouse: Optional[LighthouseServer] = None
    if lighthouse_addr is None:
        own_lighthouse = LighthouseServer(
            min_replicas=1, join_timeout_ms=10000, heartbeat_timeout_ms=5000
        )
        lighthouse_addr = own_lighthouse.address()
        print(f"[launch] embedded lighthouse at {lighthouse_addr}", flush=True)

    import socket as _socket

    hostname = _socket.gethostname()

    def spawn_group(group: int) -> List[subprocess.Popen]:
        procs = []
        store_addr = f"{hostname}:{store_port_base + group}"
        for rank in range(group_world_size):
            env = {
                **os.environ,
                **(extra_env or {}),
                "REPLICA_GROUP_ID": str(group),
                "NUM_REPLICA_GROUPS": str(num_replica_groups),
                "GROUP_RANK": str(rank),
                "GROUP_WORLD_SIZE": str(group_world_size),
                "TPUFT_LIGHTHOUSE": lighthouse_addr,
            }
            if group_world_size > 1:
                env["TPUFT_STORE_ADDR"] = store_addr
                if jax_coordinator_port_base:
                    env["TPUFT_JAX_COORDINATOR"] = (
                        f"{hostname}:{jax_coordinator_port_base + group}"
                    )
            print(
                f"[launch] starting group {group} rank {rank}: {' '.join(command)}",
                flush=True,
            )
            procs.append(subprocess.Popen(command, env=env))
        return procs

    groups = {g: spawn_group(g) for g in range(num_replica_groups)}
    # Restart timestamps per group (sliding-window exhaustion); the
    # short backoff window detects HOT loops (instant re-deaths) for the
    # exponential delay without punishing chaos kills minutes apart.
    restarts: Dict[int, List[float]] = {g: [] for g in range(num_replica_groups)}
    backoff_cap = (
        relaunch_backoff_max
        if relaunch_backoff_max is not None
        else max(8.0 * relaunch_interval, relaunch_interval)
    )
    backoff_window = max(4.0 * relaunch_interval + 5.0, 10.0)
    done: Dict[int, int] = {}
    try:
        while len(done) < num_replica_groups:
            time.sleep(min(relaunch_interval, 1.0))
            for group, procs in list(groups.items()):
                if group in done:
                    continue
                codes = [p.poll() for p in procs]
                if all(code == 0 for code in codes):
                    print(f"[launch] group {group} finished", flush=True)
                    done[group] = 0
                    continue
                failed = [code for code in codes if code not in (None, 0)]
                if not failed:
                    continue
                # Any dead rank restarts the whole group. Shared deadline so
                # a wedged multi-rank group can't stall supervision of the
                # others; after SIGKILL, reap each child so its sockets (the
                # fixed store port) are released before the respawn.
                for p in procs:
                    if p.poll() is None:
                        p.send_signal(signal.SIGTERM)
                term_deadline = time.monotonic() + 5
                for p in procs:
                    try:
                        p.wait(timeout=max(0.1, term_deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                now = time.monotonic()
                restarts[group] = prune_restart_window(
                    restarts[group], now, restart_window
                )
                if len(restarts[group]) < max_restarts:
                    rapid = len(
                        prune_restart_window(restarts[group], now, backoff_window)
                    )
                    delay = relaunch_delay(relaunch_interval, rapid, backoff_cap)
                    restarts[group].append(now)
                    print(
                        f"[launch] group {group} died (exit {failed[0]}); "
                        f"relaunch {len(restarts[group])}/{max_restarts} "
                        f"(window {restart_window:g}s) in {delay:.1f}s",
                        flush=True,
                    )
                    time.sleep(delay)
                    groups[group] = spawn_group(group)
                else:
                    print(
                        f"[launch] group {group} exhausted restarts (exit {failed[0]})",
                        flush=True,
                    )
                    done[group] = failed[0]
        return 0 if all(code == 0 for code in done.values()) else 1
    finally:
        all_procs = [p for procs in groups.values() for p in procs]
        for proc in all_procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5
        for proc in all_procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        if own_lighthouse is not None:
            own_lighthouse.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-replica-groups", type=int, required=True)
    parser.add_argument("--lighthouse", default=os.environ.get("TPUFT_LIGHTHOUSE"))
    parser.add_argument("--relaunch-interval", type=float, default=10.0)
    parser.add_argument("--max-restarts", type=int, default=100)
    parser.add_argument(
        "--restart-window",
        type=float,
        default=600.0,
        help="sliding window (seconds) for --max-restarts exhaustion; "
        "<= 0 restores the legacy lifetime count",
    )
    parser.add_argument(
        "--relaunch-backoff-max",
        type=float,
        default=None,
        help="cap on the exponential relaunch backoff (default "
        "8 x relaunch-interval)",
    )
    parser.add_argument("--group-world-size", type=int, default=1)
    parser.add_argument("--store-port-base", type=int, default=29600)
    parser.add_argument(
        "--jax-coordinator-port-base",
        type=int,
        default=0,
        help="when set, each group's ranks form one jax.distributed cluster "
        "(coordinator on this port + group id)",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER, help="-- cmd args...")
    args = parser.parse_args()
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("missing command (after --)")
    sys.exit(
        supervise(
            command,
            num_replica_groups=args.num_replica_groups,
            lighthouse_addr=args.lighthouse,
            relaunch_interval=args.relaunch_interval,
            max_restarts=args.max_restarts,
            group_world_size=args.group_world_size,
            store_port_base=args.store_port_base,
            jax_coordinator_port_base=args.jax_coordinator_port_base,
            restart_window=args.restart_window,
            relaunch_backoff_max=args.relaunch_backoff_max,
        )
    )


if __name__ == "__main__":
    main()
