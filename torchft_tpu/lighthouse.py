"""Standalone Lighthouse daemon.

Parity with the reference's ``torchft_lighthouse`` binary
(/root/reference/src/bin/lighthouse.rs): run one per job; managers point at
it via ``TPUFT_LIGHTHOUSE``. Serves the quorum/heartbeat RPCs plus an HTML
status dashboard on the same port (open http://host:port/ in a browser).

    python -m torchft_tpu.lighthouse --bind "[::]:29510" --min-replicas 2
"""

from __future__ import annotations

import argparse
import signal
import threading

from torchft_tpu.coordination import LighthouseServer


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--bind", default="[::]:29510", help="address to bind")
    parser.add_argument(
        "--min-replicas", type=int, required=True, help="minimum replicas for a quorum"
    )
    parser.add_argument(
        "--join-timeout-ms",
        type=int,
        default=60000,
        help="how long to wait for heartbeating stragglers before issuing a quorum",
    )
    parser.add_argument(
        "--quorum-tick-ms", type=int, default=100, help="quorum evaluation interval"
    )
    parser.add_argument(
        "--heartbeat-timeout-ms",
        type=int,
        default=5000,
        help="heartbeat age after which a replica is considered dead",
    )
    args = parser.parse_args()

    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
    )
    print(f"lighthouse serving on {server.address()} (dashboard: http://{server.address()}/)")

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
