"""LocalSGD and (Streaming) DiLoCo: semi-synchronous training.

Role-equivalent of the reference's ``torchft/local_sgd.py``. Both algorithms
run ``sync_every`` cheap local steps between expensive cross-replica syncs —
the natural fit for the TPU replica axis riding DCN between slices:

- :class:`LocalSGD` (reference :46-173): every ``sync_every`` steps, average
  the *parameters* across replica groups and commit.
- :class:`DiLoCo` (reference :570-797, DiLoCo https://arxiv.org/pdf/2311.08105,
  Streaming DiLoCo https://arxiv.org/pdf/2501.18512): keep a backup of the
  last-synced "global" parameters; every cycle, average the *pseudogradient*
  (global − local) for one model fragment and apply it with an outer
  optimizer (typically Nesterov SGD). Fragments rotate by manager step so all
  replicas reduce the same fragment (cross-replica deadlock avoidance,
  reference :753-764); ``fragment_sync_delay`` overlaps the allreduce with
  further local steps.

Both own (params, inner_opt_state) like :class:`torchft_tpu.optim.Optimizer`
and register their state with the manager for live healing.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from torchft_tpu.manager import Manager
from torchft_tpu.utils import netem
from torchft_tpu.utils.transfer import prefetch_to_host
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)

__all__ = ["LocalSGD", "DiLoCo", "cross_region_fleet", "region_split"]


def region_split(replica_ids: Sequence[str]) -> Dict[str, List[str]]:
    """Groups replica ids by their WAN topology region (region name ->
    ids; ``None``-region ids group under ``""``). Pure bookkeeping over
    the netem region map — the replica axis stays OUTSIDE the jax Mesh,
    so a membership change in any region never recompiles a program.
    With no topology configured every id lands in the ``""`` group (the
    single-region degenerate case)."""
    split: Dict[str, List[str]] = {}
    for rid in replica_ids:
        split.setdefault(netem.region_of(rid) or "", []).append(rid)
    return split


def cross_region_fleet() -> bool:
    """True when the configured WAN topology names more than one region —
    the signal DiLoCo uses to default its outer-sync wire to the
    quantized codec (outer syncs are the cross-region traffic; per-step
    DDP inside a region never leaves the cheap links)."""
    topo = netem.describe_topology()
    return bool(topo.get("configured")) and not topo.get("single_region", True)


def _to_device_like(host: np.ndarray, like: Any) -> Any:
    import jax.numpy as jnp

    if isinstance(like, jax.Array):
        return jax.device_put(host, like.sharding)
    return jnp.asarray(host)


def _restore_leaf_like(new: Any, like: Any, device: bool) -> Any:
    """One healed leaf onto ``like``'s layout. Routes through
    ``optim._restore_leaf`` so multi-host donor captures
    (:class:`~torchft_tpu.checkpointing._serialization.ShardedLeaf`) are
    reassembled shard-by-shard against the current sharding — plain host
    arrays land via device_put on the template's sharding."""
    import jax.numpy as jnp

    from torchft_tpu.checkpointing._serialization import ShardedLeaf
    from torchft_tpu.optim import _restore_leaf

    if isinstance(new, ShardedLeaf) or device:
        return _restore_leaf(new, like)
    if hasattr(new, "shape"):
        return np.asarray(new)
    return new


def _restore_like(state: Any, template: Any, device: bool) -> Any:
    """Restores a healed pytree onto the TEMPLATE's shardings (leaf by
    leaf) so a joiner's state lands with the same partitioning the donor
    computes with; falls back to a plain restore only on an explicit
    treedef mismatch (e.g. fresh vs restored optax state) — a leaf-level
    failure inside a matching restore must surface, not silently drop the
    shardings."""
    import jax.numpy as jnp

    from torchft_tpu.checkpointing._serialization import ShardedLeaf

    is_leaf = lambda x: isinstance(x, ShardedLeaf)  # noqa: E731
    if jax.tree_util.tree_structure(
        state, is_leaf=is_leaf
    ) != jax.tree_util.tree_structure(template):
        as_leaf = jnp.asarray if device else np.asarray

        def _fallback_leaf(x: Any) -> Any:
            # A ShardedLeaf here means a multi-host donor capture arrived
            # with a mismatched treedef: there is no template leaf to
            # reassemble its shards against, and passing the dataclass
            # through would only fail later inside jit with an opaque
            # error. Fail now, with guidance.
            if isinstance(x, ShardedLeaf):
                raise ValueError(
                    "healed state contains a multi-host ShardedLeaf but its "
                    "tree structure does not match the local template; "
                    "donor and joiner opt-state structures must match for "
                    "multi-host heal (construct the joiner's optimizer "
                    "state with the same optax chain before healing)"
                )
            return as_leaf(x) if hasattr(x, "shape") else x

        return jax.tree_util.tree_map(_fallback_leaf, state, is_leaf=is_leaf)
    return jax.tree_util.tree_map(
        lambda x, like: _restore_leaf_like(x, like, device),
        state,
        template,
        is_leaf=is_leaf,
    )


class LocalSGD:
    """Parameter-averaging semi-sync training (reference local_sgd.py:46-173).

    Runs the inner optimizer every step; every ``sync_every`` steps averages
    the parameters across replica groups and commits. A failed commit keeps
    the local parameters and retries at the next sync point.
    """

    def __init__(
        self,
        manager: Manager,
        inner_tx: Any,
        params: Any,
        sync_every: int,
        register_key: str = "local_sgd",
    ) -> None:
        assert sync_every >= 1
        self._manager = manager
        self._inner_tx = inner_tx
        self.params = params
        self.opt_state = inner_tx.init(params)
        self._sync_every = sync_every
        self._local_step = 0
        manager.register_state_dict_fn(register_key, self._load_state, self._save_state)

        from torchft_tpu.optim import make_jit_update

        # One fused dispatch per inner step (hot path: sync_every - 1 of
        # every sync_every steps touch no network at all).
        self._jit_update = make_jit_update(inner_tx)

    def _save_state(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state}

    # tpuft: allow(lock-discipline): heal apply — runs under the state-dict writer taken by Manager._apply_pending_state_dict
    def _load_state(self, state: Dict[str, Any]) -> None:
        # Sharding-preserving restore (see _restore_like).
        self.params = _restore_like(state["params"], self.params, device=True)
        self.opt_state = _restore_like(
            state["opt_state"], self.opt_state, device=True
        )

    def step(self, grads: Any) -> bool:
        """One inner step; returns whether a sync round committed."""
        # Write-lock mutations so checkpoint captures never see a torn state
        # (reference step pre/post hooks, local_sgd.py:112-128).
        self._manager.disallow_state_dict_read()
        try:
            self.params, self.opt_state = self._jit_update(
                grads, self.opt_state, self.params
            )
        finally:
            self._manager.allow_state_dict_read()
        return self._after_inner_step()

    def make_step_fn(self, loss_fn: Any):
        """``step_fn(*batch) -> (loss, synced)``: the inner step as ONE
        fused jitted dispatch (loss+grad+update — sync_every−1 of every
        sync_every steps touch no network, so their cost is exactly the
        plain train step), with the parameter-averaging sync at the
        boundary. ``loss_fn(params, *batch) -> scalar``. Mirrors
        ``DiLoCo.make_step_fn`` / ``Optimizer.make_step_fn``."""
        from torchft_tpu.optim import make_jit_fused_step

        fused = make_jit_fused_step(self._inner_tx, loss_fn)

        def step_fn(*batch):
            self._manager.disallow_state_dict_read()
            try:
                loss, self.params, self.opt_state = fused(
                    self.params, self.opt_state, *batch
                )
            finally:
                self._manager.allow_state_dict_read()
            return loss, self._after_inner_step()

        return step_fn

    def _after_inner_step(self) -> bool:
        """Shared sync-boundary bookkeeping for step()/make_step_fn()."""
        self._local_step += 1
        if self._local_step < self._sync_every:
            return False
        self._local_step = 0
        return self._sync()

    def _sync(self) -> bool:
        # Shard-preserving parameter averaging (parallel/mesh.py): each
        # rank stages its OWN addressable shards, reduces them with the
        # same-rank shards in the other replica groups, and reassembles
        # onto the original shardings — so LocalSGD composes with
        # multi-host fsdp/tp state (a whole-leaf host fetch would raise on
        # non-fully-addressable arrays and lose the shardings on restore).
        from torchft_tpu.parallel.mesh import ft_allreduce_sharded

        self._manager.start_quorum()
        averaged = ft_allreduce_sharded(self._manager, self.params)
        if self._manager.should_commit():
            self._manager.disallow_state_dict_read()
            try:
                self.params = averaged
            finally:
                self._manager.allow_state_dict_read()
            return True
        return False


class _Fragment:
    """One model fragment's DiLoCo state: the backup of the last-synced
    global parameters, the outer optimizer state, and the in-flight
    pseudogradient allreduce (reference _StreamingDiLoCoFragment:176-568).

    Two sync pipelines:
    - plain (``should_quantize=False``): host-numpy pseudogradients through
      ``manager.allreduce_pytree`` (the reference's default path);
    - quantized (``should_quantize=True``): TPU-first — the backup lives on
      device, pseudogradient + fp8 quantization run as one jitted kernel
      (Pallas on TPU), and only the fp8 payload + block scales cross the
      host boundary (~4x less traffic than f32), riding
      :func:`allreduce_quantized_wire` between replica groups.
    """

    def __init__(
        self,
        manager: Manager,
        fragment_id: int,
        leaf_indices: List[int],
        outer_tx: Any,
        initial_leaves: List[Any],
        should_quantize: bool,
        fragment_update_alpha: float,
    ) -> None:
        import jax.numpy as jnp

        self._manager = manager
        self._fragment_id = fragment_id
        self.leaf_indices = leaf_indices
        self._outer_tx = outer_tx
        self._should_quantize = should_quantize
        self._alpha = fragment_update_alpha
        if should_quantize:
            # Device-resident backup (HBM): no host copy in the hot path.
            self.backup: List[Any] = [jnp.asarray(x) for x in initial_leaves]
        else:
            # Host backup (the "CPU-pinned" analogue of the reference).
            # Requires fully-addressable leaves: fail at construction with
            # guidance rather than deep inside the first sync.
            for x in initial_leaves:
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    raise ValueError(
                        "DiLoCo's host (non-quantized) pipeline needs "
                        "fully-addressable parameters; for multi-host "
                        "sharded state use should_quantize=True (the "
                        "device pipeline keeps backups sharded on the "
                        "group mesh)"
                    )
            self.backup = [np.array(x, copy=True) for x in initial_leaves]
        self.outer_opt_state = outer_tx.init(self.backup)
        if not should_quantize:
            from torchft_tpu.optim import make_jit_update

            # The host path's outer step still goes through ONE jitted
            # dispatch (the unjitted-optax invariant): an eager optax
            # update issues hundreds of tiny ops on the default backend,
            # which dominates on tunneled devices. The quantized path's
            # outer step is fused into _jit_apply_outer below.
            self._jit_outer_update = make_jit_update(outer_tx)
        self._work: Optional[Work] = None
        manager.register_state_dict_fn(
            f"StreamingDiLoCoFragment_{fragment_id}", self._load_state, self._save_state
        )

        if should_quantize:
            self._build_device_pipeline()

    def _build_device_pipeline(self) -> None:
        """Jitted device kernels for the quantized path (shared fp8 codec)."""
        import jax.numpy as jnp

        from torchft_tpu.ops.quantization import make_tree_fp8_codec

        _, dequantize = make_tree_fp8_codec(self.backup)
        outer_tx = self._outer_tx
        alpha = self._alpha

        def quantize_pseudograd(backup_leaves, local_leaves):
            from torchft_tpu.ops.quantization import quantize_blocks_device

            flat = jnp.concatenate(
                [
                    (b.astype(jnp.float32) - l.astype(jnp.float32)).reshape(-1)
                    for b, l in zip(backup_leaves, local_leaves)
                ]
            )
            return quantize_blocks_device(flat)

        def apply_outer(payload, scales, backup_leaves, local_leaves, outer_state):
            import optax

            avg_pg = dequantize(payload, scales)
            updates, new_state = outer_tx.update(avg_pg, outer_state, backup_leaves)
            new_backup = optax.apply_updates(backup_leaves, updates)
            merged = [
                (g.astype(jnp.float32) * (1.0 - alpha)
                 + l.astype(jnp.float32) * alpha).astype(g.dtype)
                for g, l in zip(new_backup, local_leaves)
            ]
            return new_backup, merged, new_state

        self._jit_quantize_pg = jax.jit(quantize_pseudograd)
        self._jit_apply_outer = jax.jit(apply_outer)

    def _save_state(self) -> Dict[str, Any]:
        # Device backups are handed over as-is: the checkpoint transport
        # host-converts every leaf at staging time (ShardedLeaf capture for
        # non-fully-addressable arrays — an eager np.array here would RAISE
        # on multi-host shardings). Host backups are snapshotted since the
        # list is rebound, never mutated, on sync.
        return {
            "original_parameters": (
                list(self.backup)
                if self._should_quantize
                else [np.array(b) for b in self.backup]
            ),
            "outer_optimizer": self.outer_opt_state,
        }

    # tpuft: allow(lock-discipline): heal apply — runs under the state-dict writer taken by Manager._apply_pending_state_dict
    def _load_state(self, state: Dict[str, Any]) -> None:
        # Healing must restore SHARDING, not just values: the joiner's
        # pre-heal backups carry the model's fsdp/tp shardings, and a plain
        # jnp.asarray restore would leave the healed state replicated — the
        # joiner's jitted programs would then partition differently from the
        # donor's, and their reductions drift by an ulp per sync (breaking
        # the bitwise cross-replica invariant the integration tests assert).
        # Multi-host donor captures arrive as ShardedLeaf and reassemble
        # against the current backup's sharding (_restore_leaf_like).
        restored = state["original_parameters"]
        if len(restored) != len(self.backup):
            raise ValueError(
                f"healed fragment has {len(restored)} leaves, expected "
                f"{len(self.backup)}: donor/joiner fragment partitioning "
                "must match"
            )
        if self._should_quantize:
            self.backup = [
                _restore_leaf_like(b, like, device=True)
                for b, like in zip(restored, self.backup)
            ]
        else:
            self.backup = [np.array(b) for b in restored]
        self.outer_opt_state = _restore_like(
            state["outer_optimizer"],
            self.outer_opt_state,
            device=self._should_quantize,
        )

    def prepare_sync(self, local_leaves: List[Any]) -> None:
        """Computes pseudogradients (backup − local) and launches their
        averaging; does not wait (reference :402-421)."""
        assert self._work is None, "fragment already has an allreduce in flight"
        if self._should_quantize:
            payload, scales = self._jit_quantize_pg(
                self.backup, [local_leaves[i] for i in self.leaf_indices]
            )
            # Device arrays pass through: the d2h fetch happens on the
            # pipeline thread, overlapping the delay window's inner steps.
            # Participation zeroing + error funnel live in the manager.
            self._work = self._manager.allreduce_prequantized(payload, scales)
        else:
            locals_ = [local_leaves[i] for i in self.leaf_indices]
            # Launch every device→host copy before consuming any: the
            # per-leaf np.asarray below then drains transfers already in
            # flight instead of serializing one round trip per leaf.
            prefetch_to_host(locals_)
            pseudograds = [
                backup - np.asarray(leaf)
                for backup, leaf in zip(self.backup, locals_)
            ]
            self._work = self._manager.allreduce_pytree(pseudograds)

    def perform_sync(self, local_leaves: List[Any]) -> bool:
        """Waits for the allreduce, restores globals, commits, and on success
        applies the outer step + local/global merge (reference :423-476)."""
        assert self._work is not None, "perform_sync before prepare_sync"
        averaged = self._work.wait()
        self._work = None

        locals_ = [local_leaves[i] for i in self.leaf_indices]
        if not self._should_quantize:
            # Same launch-then-drain pattern as prepare_sync: this fetch sits
            # on the commit critical path right after wait().
            prefetch_to_host(locals_)
        local_copy = [
            leaf if self._should_quantize else np.asarray(leaf)
            for leaf in locals_
        ]
        # Restore to the last global state before voting: on a failed commit
        # the fragment resets rather than over-training on a divergent copy.
        self._manager.disallow_state_dict_read()
        try:
            for slot, backup in enumerate(self.backup):
                local_leaves[self.leaf_indices[slot]] = (
                    backup
                    if self._should_quantize
                    else _to_device_like(backup, local_leaves[self.leaf_indices[slot]])
                )
        finally:
            self._manager.allow_state_dict_read()

        # The commit barrier must run unlocked: it can apply a healing state
        # dict and peers' serve threads need the read lock meanwhile.
        if not self._manager.should_commit():
            return False
        if averaged is None:  # quantized-path allreduce error (already reported)
            return False

        self._manager.disallow_state_dict_read()
        try:
            if self._should_quantize:
                import jax.numpy as jnp

                payload, scales = averaged
                # The averaged wire payload arrives as a HOST array on every
                # local rank. With a multi-rank group the backups are global
                # arrays over the group's mesh, and a plain jnp.asarray
                # would make the payload process-LOCAL — mixed local/global
                # inputs desync the ranks' jitted programs (one raises, the
                # peer enters the collective: deadlock). Restore it
                # REPLICATED on the backup's mesh; every rank holds the
                # identical averaged bytes, so the replicated device_put is
                # consistent by construction.
                mesh = (
                    getattr(self.backup[0].sharding, "mesh", None)
                    if isinstance(self.backup[0], jax.Array)
                    else None
                )
                if mesh is not None and len(mesh.devices.flat) > 1:
                    from jax.sharding import NamedSharding, PartitionSpec

                    replicated = NamedSharding(mesh, PartitionSpec())
                    payload = jax.device_put(np.asarray(payload), replicated)
                    scales = jax.device_put(np.asarray(scales), replicated)
                else:
                    payload = jnp.asarray(payload)
                    scales = jnp.asarray(scales)
                new_backup, merged, self.outer_opt_state = self._jit_apply_outer(
                    payload,
                    scales,
                    self.backup,
                    local_copy,
                    self.outer_opt_state,
                )
                self.backup = list(new_backup)
                for slot, i in enumerate(self.leaf_indices):
                    local_leaves[i] = merged[slot]
            else:
                new_global, self.outer_opt_state = self._jit_outer_update(
                    averaged, self.outer_opt_state, self.backup
                )
                new_global = [np.asarray(g) for g in new_global]
                self.backup = [np.array(g, copy=True) for g in new_global]
                for slot, i in enumerate(self.leaf_indices):
                    merged = (
                        new_global[slot] * (1.0 - self._alpha)
                        + local_copy[slot] * self._alpha
                    )
                    local_leaves[i] = _to_device_like(
                        merged.astype(local_copy[slot].dtype), local_leaves[i]
                    )
        finally:
            self._manager.allow_state_dict_read()
        return True


class DiLoCo:
    """(Streaming) DiLoCo over the fault-tolerant replica axis.

    Args:
        manager: must use synchronous quorum (``use_async_quorum=False``).
        inner_tx / outer_tx: optax transforms for the local and global steps.
            ``outer_tx`` may be a list, one per fragment. The canonical outer
            optimizer is SGD with Nesterov momentum.
        params: initial parameters (owned by this object, like Optimizer).
        sync_every: inner steps per full round of fragment syncs; must be a
            multiple of ``n_fragments``.
        n_fragments: number of streaming fragments (leaf-partitioned).
        fragment_fn: optional override partitioning flattened leaf indices
            into fragments; defaults to contiguous chunks.
        fragment_sync_delay: inner steps between a fragment's allreduce
            launch and its blocking sync (tau in the Streaming DiLoCo paper).
        fragment_update_alpha: local/global mix after a sync (0 = take the
            global params, 1 = keep local).
        should_quantize: quantize the outer-sync wire (fp8 allreduce).
            ``None`` (the default) auto-resolves from the WAN topology
            map: a fleet spanning >1 region quantizes its outer syncs
            (they are the traffic that crosses the expensive inter-region
            links — per-step DDP stays intra-region by construction),
            a single-region or topology-less fleet keeps the full-
            precision wire, exactly the pre-topology default. The split
            comes from the same netem region map as everything else and
            NEVER becomes a jax Mesh axis — membership changes must not
            recompile.
    """

    def __init__(
        self,
        manager: Manager,
        inner_tx: Any,
        outer_tx: Any,
        params: Any,
        sync_every: int,
        n_fragments: int = 1,
        fragment_fn: Optional[Callable[[int], List[List[int]]]] = None,
        should_quantize: Optional[bool] = None,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        if sync_every < n_fragments:
            raise ValueError("Only 1 fragment can be synchronized at a time")
        if sync_every % n_fragments != 0:
            raise ValueError("sync_every must be a multiple of n_fragments")
        self._sync_every = sync_every // n_fragments
        if fragment_sync_delay >= self._sync_every:
            raise ValueError("Fragment must be synced before it is reduced again")
        if not 0.0 <= fragment_update_alpha <= 1.0:
            raise ValueError("fragment_update_alpha must be between 0 and 1")

        if should_quantize is None:
            should_quantize = cross_region_fleet()
            if should_quantize:
                logger.info(
                    "DiLoCo: WAN topology spans multiple regions; outer "
                    "syncs ride the quantized wire (pass "
                    "should_quantize=False to override)"
                )

        self._manager = manager
        self._inner_tx = inner_tx
        self._fragment_sync_delay = fragment_sync_delay
        self._local_step = 0

        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._leaves = list(leaves)
        self.inner_opt_state = inner_tx.init(params)
        manager.register_state_dict_fn(
            "diloco_inner", self._load_inner, self._save_inner
        )

        from torchft_tpu.optim import make_jit_update

        # One fused dispatch per inner step; everything else in the inner
        # loop is pure python bookkeeping.
        self._jit_update = make_jit_update(inner_tx)

        if fragment_fn is not None:
            partitions = fragment_fn(len(self._leaves))
        else:
            # Contiguous leaf chunks (the analogue of layer-group fragments).
            partitions = [
                [int(j) for j in part]
                for part in np.array_split(np.arange(len(self._leaves)), n_fragments)
            ]
        assert len(partitions) == n_fragments
        outer_txs = outer_tx if isinstance(outer_tx, list) else [outer_tx] * n_fragments
        assert len(outer_txs) == n_fragments
        self._fragments = [
            _Fragment(
                manager,
                i,
                part,
                outer_txs[i],
                [self._leaves[j] for j in part],
                should_quantize,
                fragment_update_alpha,
            )
            for i, part in enumerate(partitions)
        ]

    # -- state -------------------------------------------------------------

    @property
    def params(self) -> Any:
        return jax.tree_util.tree_unflatten(self._treedef, self._leaves)

    def _save_inner(self) -> Dict[str, Any]:
        return {"leaves": list(self._leaves), "opt_state": self.inner_opt_state}

    # tpuft: allow(lock-discipline): heal apply — runs under the state-dict writer taken by Manager._apply_pending_state_dict
    def _load_inner(self, state: Dict[str, Any]) -> None:
        # Restore onto the existing leaves' shardings (see
        # _restore_leaf_like): a healed joiner must end up with the same
        # partitioning the donor computes with, or their jitted programs
        # diverge by an ulp. Multi-host donor captures (ShardedLeaf)
        # reassemble against the current leaves' shardings.
        old = self._leaves
        new = state["leaves"]
        if len(old) != len(new):
            raise ValueError(
                f"healed inner state has {len(new)} leaves, expected "
                f"{len(old)}: donor/joiner models must match"
            )
        self._leaves = [
            _restore_leaf_like(x, like, device=True) for x, like in zip(new, old)
        ]
        self.inner_opt_state = _restore_like(
            state["opt_state"], self.inner_opt_state, device=True
        )

    def _current_fragment(self) -> int:
        """All replicas must reduce the same fragment per round; keyed by the
        committed manager step (reference :739-744)."""
        return self._manager.current_step() % len(self._fragments)

    # -- step --------------------------------------------------------------

    def step(self, grads: Any) -> bool:
        """One inner step; drives the fragment prepare/sync schedule.
        Returns whether a fragment sync committed this step."""
        # Write-lock the inner mutation (reference step pre/post hooks).
        self._manager.disallow_state_dict_read()
        try:
            new_params, self.inner_opt_state = self._jit_update(
                grads, self.inner_opt_state, self.params
            )
            self._leaves = list(jax.tree_util.tree_flatten(new_params)[0])
        finally:
            self._manager.allow_state_dict_read()
        return self._after_inner_step()

    def make_step_fn(self, loss_fn: Callable[..., Any]) -> Callable[..., Any]:
        """Fuses loss/grad + inner update into ONE jitted dispatch.

        ``loss_fn(params, *batch) -> scalar loss``. Returns
        ``step(*batch) -> (loss, committed)``; the returned callable owns the
        same prepare/sync schedule as :meth:`step`. Halving the dispatch
        count matters on high-latency device links, and XLA fuses the
        backward with the optimizer update (no grad materialization in HBM
        between them)."""
        import optax

        inner_tx = self._inner_tx
        treedef = self._treedef

        def fused(leaves: List[Any], opt_state: Any, *batch: Any):
            params = jax.tree_util.tree_unflatten(treedef, leaves)
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            updates, new_state = inner_tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return jax.tree_util.tree_flatten(new_params)[0], new_state, loss

        fused_jit = jax.jit(fused)

        def step(*batch: Any):
            self._manager.disallow_state_dict_read()
            try:
                new_leaves, self.inner_opt_state, loss = fused_jit(
                    self._leaves, self.inner_opt_state, *batch
                )
                self._leaves = list(new_leaves)
            finally:
                self._manager.allow_state_dict_read()
            return loss, self._after_inner_step()

        return step

    def _after_inner_step(self) -> bool:
        """Shared fragment prepare/sync schedule (runs after every inner
        update); returns whether a fragment sync committed."""
        self._local_step += 1
        committed = False

        if self._local_step == self._sync_every - self._fragment_sync_delay:
            self._manager.start_quorum()
            fragment = self._current_fragment()
            logger.info("Preparing fragment=%d step=%d", fragment, self._local_step)
            self._fragments[fragment].prepare_sync(self._leaves)

        if self._local_step == self._sync_every:
            fragment = self._current_fragment()
            logger.info(
                "Syncing fragment=%d step=%d manager_step=%d",
                fragment,
                self._local_step,
                self._manager.current_step(),
            )
            committed = self._fragments[fragment].perform_sync(self._leaves)
            self._local_step = 0
        return committed
