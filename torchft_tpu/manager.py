"""Manager: the per-rank fault-tolerance state machine.

Role-equivalent of the reference's ``torchft/manager.py:137`` — the heart of
the library. Embedded in the train loop, it:

- computes quorums (async, overlapped with the forward pass) via the native
  ManagerServer/Lighthouse plane;
- reconfigures the replica-axis process group when membership changes
  (``configure`` under a fresh store prefix keyed by quorum_id);
- runs fault-tolerant gradient allreduces: zeros contributions from
  non-participating replicas, converts AVG to SUM + divide by the live
  participant count so numerics stay N-independent, and swallows collective
  errors into a sticky per-step error state;
- live-heals joining replicas by streaming the state pytree from a healthy
  donor via a :class:`CheckpointTransport`;
- arbitrates per-step commits via the all-local-rank AND barrier
  (``should_commit``), incrementing the step only on quorum-wide success.

Step protocol (see also optim.OptimizerWrapper)::

    manager.start_quorum()          # before forward
    grads = grad_fn(params, batch)  # forward/backward
    work = manager.allreduce_pytree(grads)
    grads = work.wait()
    if manager.should_commit():     # commit barrier
        params = apply_update(params, grads)

On TPU the collectives here ride host DCN between replica groups
(parallel/process_group.py); intra-slice collectives stay inside the jitted
step as XLA psums over the device mesh (parallel/mesh.py).
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import math
import os
import socket
import threading
import time
import traceback
import uuid
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, TypeVar, cast

import numpy as np

from torchft_tpu import goodput as goodput_plane
from torchft_tpu import health as health_plane
from torchft_tpu import metrics, tracing
from torchft_tpu.checkpointing import (
    CheckpointTransport,
    HTTPTransport,
    heal_delta_enabled,
    heal_stripe_enabled,
    heal_stripe_max_donors,
)
from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.coordination import ManagerClient, ManagerServer
from torchft_tpu.history import WeightHistory
from torchft_tpu.parallel.process_group import ProcessGroup, ReduceOp
from torchft_tpu.parallel.store import StoreClient
from torchft_tpu.telemetry import commits_logger, errors_logger, quorums_logger
from torchft_tpu.utils import lockcheck, netem, schedules
from torchft_tpu.utils.profiling import trace_span
from torchft_tpu.utils.transfer import prefetch_to_host
from torchft_tpu.work import Work, _DummyWork

T = TypeVar("T")

logger = logging.getLogger(__name__)

__all__ = [
    "Manager",
    "WorldSizeMode",
    "ExceptionWithTraceback",
    "HealExhaustedError",
    "DegradedReplicaError",
]

# Re-exported for train loops/supervisors that catch the escalation
# family in one place (quorum timeout / HealExhaustedError /
# DegradedReplicaError all mean "supervisor territory").
DegradedReplicaError = health_plane.DegradedReplicaError

# Env overrides (reference: manager.py:82-89).
TIMEOUT_SEC_ENV = "TPUFT_TIMEOUT_SEC"
QUORUM_TIMEOUT_SEC_ENV = "TPUFT_QUORUM_TIMEOUT_SEC"
CONNECT_TIMEOUT_SEC_ENV = "TPUFT_CONNECT_TIMEOUT_SEC"
QUORUM_RETRIES_ENV = "TPUFT_QUORUM_RETRIES"
LIGHTHOUSE_ENV = "TPUFT_LIGHTHOUSE"
MANAGER_PORT_ENV = "TPUFT_MANAGER_PORT"
COMMIT_PIPELINE_ENV = "TPUFT_COMMIT_PIPELINE"
COMMIT_PIPELINE_DEPTH_ENV = "TPUFT_COMMIT_PIPELINE_DEPTH"
COMMIT_PIPELINE_ADAPTIVE_ENV = "TPUFT_COMMIT_PIPELINE_ADAPTIVE"
HEAL_MAX_ATTEMPTS_ENV = "TPUFT_HEAL_MAX_ATTEMPTS"

# Adaptive-mode ceiling when $TPUFT_COMMIT_PIPELINE_ADAPTIVE is unset. The
# snapshot ring holds one (params, opt_state) copy per window slot, so the
# ceiling is a memory bound, not a latency one — doctor warns past 8.
DEFAULT_ADAPTIVE_MAX_DEPTH = 4


def _env_timeout(env: str, default: float) -> float:
    value = os.environ.get(env)
    return float(value) if value is not None else default


class WorldSizeMode(Enum):
    """Numerics policy when more than ``min_replica_size`` replicas are live
    (reference: manager.py:112-127).

    DYNAMIC: world size grows to all available replicas; gradients are
        normalized by the live count.
    FIXED_WITH_SPARES: exactly ``min_replica_size`` replicas participate;
        spares contribute zero gradients and are normalized away.
    """

    DYNAMIC = 0
    FIXED_WITH_SPARES = 1


class HealExhaustedError(RuntimeError):
    """Raised out of the quorum future (``wait_quorum``/``start_quorum``)
    when ``TPUFT_HEAL_MAX_ATTEMPTS`` consecutive heal attempts all failed:
    this replica cannot catch up from any donor it is being assigned, so —
    like a quorum timeout or the ``max_retries`` commit RuntimeError — it
    escalates past the step boundary into supervisor-restart territory
    instead of looping on a heal that will never land."""


class _DonorRecentlyFailed(Exception):
    """Internal: the assigned recovery donor failed us on the immediately
    preceding attempt; fail this heal round fast (no transfer) so the next
    quorum round can rotate the assignment. One-shot per failure — a
    consecutive reassignment of the same donor is attempted for real."""


def storm_stripe_rotation(
    replica_id: str,
    joining_replica_ids: List[str],
    group_rank: int,
    quorum_id: int,
) -> int:
    """The coordinated mass-rejoin-storm stripe offset: a pure function of
    the joiner's identity inside the quorum view — its ordinal among the
    joining members (sorted replica ids, so every observer derives the
    same ordering from the same quorum), its group rank, and the quorum
    id. No negotiation, no randomness, same spirit as the ZeRO
    ``shard_assignment``: N joiners healing in the same era derive N
    distinct offsets and seed their stripe plans at different donors
    instead of all hammering donor 0's first stripe simultaneously. A
    replica not in the joining list (or a lone joiner) degrades to the
    pre-storm rotation — a function of (group rank, quorum id) alone."""
    ordinal = 0
    if replica_id in joining_replica_ids:
        ordinal = sorted(joining_replica_ids).index(replica_id)
    return ordinal + max(group_rank, 0) + max(int(quorum_id), 0)


class ExceptionWithTraceback(Exception):
    """Carries a worker-thread exception across the report_error funnel with
    its formatted stack attached, so the thread hop cannot strand the
    traceback (reference manager.py:130-134 behavior).

    Formats from the exception's own ``__traceback__`` rather than the
    ambient ``format_exc`` state, so wrapping works from any thread — not
    only inside the original ``except`` block."""

    def __init__(self, e: Exception) -> None:
        tb = "".join(traceback.format_exception(type(e), e, e.__traceback__))
        super().__init__(f"{e}\n{tb}")
        self.original_exception = e
        self.stack_trace: str = tb


class _TrackedCommitFuture:
    """Proxy around should_commit_async's executor future that records
    whether the caller ever observed its outcome, so start_quorum's drain
    can tell "caller already handled the barrier result/exception" (skip)
    from "caller never looked" (drain, propagating any stored exception).

    A RESTRICTED future proxy, not a concurrent.futures.Future subclass:
    it supports result/exception/done/running/cancelled/cancel/
    add_done_callback, but not the module-level ``concurrent.futures.wait``
    / ``as_completed`` helpers (which poke Future internals). Callers
    coordinating multiple futures should resolve this one directly."""

    def __init__(self, inner: concurrent.futures.Future) -> None:
        self._inner = inner
        self.consumed = False

    def result(self, timeout: Optional[float] = None) -> Any:
        # Only a DELIVERED outcome (value or the barrier's own exception)
        # counts as consumption: a wait that merely timed out — or was cut
        # short by KeyboardInterrupt/SystemExit — observed nothing, and
        # checking done() after the fact would race a barrier completing
        # just after the wait expires. Future re-raises the stored
        # exception OBJECT itself, so identity against the stored exception
        # tells a delivered outcome from an interrupted wait.
        try:
            value = self._inner.result(timeout)
        except BaseException as e:
            try:
                delivered = (
                    self._inner.done() and self._inner.exception(timeout=0) is e
                )
            except concurrent.futures.CancelledError:
                delivered = False
            if delivered:
                self.consumed = True
            raise
        self.consumed = True
        return value

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        # Future.exception RETURNS a stored exception and only raises
        # TimeoutError/CancelledError for the wait itself, so any return
        # means the outcome was delivered.
        exc = self._inner.exception(timeout)
        self.consumed = True
        return exc

    def done(self) -> bool:
        return self._inner.done()

    def running(self) -> bool:
        return self._inner.running()

    def cancelled(self) -> bool:
        return self._inner.cancelled()

    def cancel(self) -> bool:
        # A cancelled barrier was observed by whoever cancelled it.
        cancelled = self._inner.cancel()
        if cancelled:
            self.consumed = True
        return cancelled

    def add_done_callback(self, fn: Callable[[Any], None]) -> None:
        self._inner.add_done_callback(lambda _inner: fn(self))


class _SpeculativeCommitFuture:
    """Verdict future for one slot of the depth-N speculative window.

    The barrier RPC rides the manager's commit pool so the whole window's
    votes overlap on the wire (the single-thread quorum executor would
    serialize them — the depth-1 path keeps it for its FIFO ordering
    guarantees). The step/commit ACCOUNTING that ``should_commit`` applies
    inline is deferred to the first ``result()`` delivery: the pipelined
    optimizer resolves records oldest-first, so accounting applies in
    window order on the consuming thread. ``discard()`` consumes the
    verdict WITHOUT accounting — a rollback unwound this slot, so quorum-
    wide the step never happened (every survivor discards the same
    suffix, keeping fleet accounting in lockstep)."""

    __slots__ = (
        "_manager", "_inner", "claimed_step", "local_vote",
        "_participants", "_lock", "_settled",
    )

    def __init__(
        self,
        manager: "Manager",
        inner: concurrent.futures.Future,
        claimed_step: int,
        local_vote: bool,
        participants: int,
    ) -> None:
        self._manager = manager
        self._inner = inner
        self.claimed_step = claimed_step
        self.local_vote = local_vote
        self._participants = participants
        self._lock = threading.Lock()
        self._settled = False

    def result(self, timeout: Optional[float] = None) -> bool:
        verdict = bool(self._inner.result(timeout))
        with self._lock:
            settle = not self._settled
            self._settled = True
        if settle:
            # May raise (max_retries escalation) — after marking settled,
            # so a re-read returns the verdict instead of double-counting.
            self._manager._speculative_commit_resolved(
                self.claimed_step, verdict, self._participants
            )
        return verdict

    def done(self) -> bool:
        return self._inner.done()

    def discard(self) -> None:
        """Consumes the barrier verdict with NO step accounting (and no
        exception): the window unwound past this slot. Best-effort
        bounded wait — an unreachable barrier here is already a poisoned
        step through the normal error funnels."""
        with self._lock:
            self._settled = True
        try:
            self._inner.result(self._manager._timeout)
        except Exception:  # noqa: BLE001 — the slot is unwound either way
            pass


class Manager:
    """Fault tolerance manager for one rank of one replica group.

    Args:
        pg: the replica-axis process group (reconfigured on quorum change).
        min_replica_size: minimum replicas for a step to commit.
        store: rendezvous store client for this replica group (local-rank
            coordination + advertised to peers for PG rendezvous).
        store_addr: the group store's "host:port" advertised to other groups.
        load_state_dict/state_dict: legacy single-key state registration;
            prefer :meth:`register_state_dict_fn`.
        use_async_quorum: overlap quorum with the forward pass; the joining
            replica skips participation for one step instead of blocking all.
        replica_id: stable prefix for this group's identity; a uuid suffix is
            appended per process lifetime.
        group_rank/group_world_size: this process's coordinates inside the
            replica group (host index / hosts per group).
        commit_pipeline_depth: 0 (default) resolves every step's commit
            before the next dispatch; N >= 1 opts into the pipelined-commit
            schedule with an N-step bounded speculative window (the
            phantom-commit envelope grows with N — see
            optim.Optimizer.make_step_fn); the string ``"auto"`` picks the
            depth adaptively per quorum era from the measured control-plane
            RTT vs step time (capped by
            ``$TPUFT_COMMIT_PIPELINE_ADAPTIVE``, default
            ``DEFAULT_ADAPTIVE_MAX_DEPTH``).
            ``$TPUFT_COMMIT_PIPELINE_DEPTH`` overrides (int or ``auto``);
            the legacy ``$TPUFT_COMMIT_PIPELINE`` is honored when the new
            var is unset.
        heal_max_attempts: consecutive failed heal attempts tolerated
            before :class:`HealExhaustedError` escalates out of the quorum
            future (``$TPUFT_HEAL_MAX_ATTEMPTS`` overrides). Each failed
            attempt funnels into :meth:`report_error` (the step does not
            commit, the joiner re-enters the next quorum still joining);
            a DEAD donor leaves the pool via heartbeat expiry, so the next
            assignment naturally excludes it, and the transport's resume
            cache re-fetches only the chunks the failed attempt did not
            verify.
    """

    def __init__(
        self,
        pg: ProcessGroup,
        min_replica_size: int,
        store: StoreClient,
        store_addr: str,
        load_state_dict: Optional[Callable[[T], None]] = None,
        state_dict: Optional[Callable[[], T]] = None,
        use_async_quorum: bool = True,
        timeout: float = 60.0,
        quorum_timeout: float = 60.0,
        connect_timeout: float = 10.0,
        group_rank: Optional[int] = None,
        group_world_size: Optional[int] = None,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        lighthouse_addr: Optional[str] = None,
        replica_id: Optional[str] = None,
        manager_bind: str = "[::]:0",
        hostname: str = "",
        heartbeat_interval: float = 0.1,
        checkpoint_transport: Optional[CheckpointTransport] = None,
        init_sync: bool = True,
        max_retries: Optional[int] = None,
        quorum_retries: int = 0,
        commit_pipeline_depth: Any = 0,
        heal_max_attempts: int = 5,
        health_monitor: Optional[Any] = None,
    ) -> None:
        self._pg = pg
        self._min_replica_size = min_replica_size
        self._timeout = _env_timeout(TIMEOUT_SEC_ENV, timeout)
        self._quorum_timeout = _env_timeout(QUORUM_TIMEOUT_SEC_ENV, quorum_timeout)
        self._connect_timeout = _env_timeout(CONNECT_TIMEOUT_SEC_ENV, connect_timeout)
        self._quorum_retries = int(
            os.environ.get(QUORUM_RETRIES_ENV, str(quorum_retries))
        )
        # Pipelined commit (opt-in): up to depth-N steps' device syncs +
        # commit votes may resolve while younger steps are already
        # dispatched — optim.make_step_fn reads this depth and runs its
        # pipelined schedule over an N-step bounded speculative window
        # (rollback snapshots become a ring, the phantom-commit envelope
        # grows to at most N steps; see optim.py). "auto" picks the depth
        # per quorum era from the measured control-plane RTT vs step time;
        # TPUFT_STRICT_COMMIT=1 overrides any depth back to 0.
        raw_depth: Any = os.environ.get(COMMIT_PIPELINE_DEPTH_ENV)
        if raw_depth is None:
            raw_depth = os.environ.get(COMMIT_PIPELINE_ENV)
        if raw_depth is None:
            raw_depth = commit_pipeline_depth
        self._commit_pipeline_adaptive = (
            isinstance(raw_depth, str) and raw_depth.strip().lower() == "auto"
        )
        try:
            self._adaptive_max_depth = max(
                1,
                int(
                    os.environ.get(
                        COMMIT_PIPELINE_ADAPTIVE_ENV,
                        str(DEFAULT_ADAPTIVE_MAX_DEPTH),
                    )
                ),
            )
        except ValueError:
            self._adaptive_max_depth = DEFAULT_ADAPTIVE_MAX_DEPTH
        if self._commit_pipeline_adaptive:
            self._commit_pipeline_depth = 1  # deepens as evidence arrives
        else:
            try:
                self._commit_pipeline_depth = int(raw_depth)
            except (TypeError, ValueError):
                raise ValueError(
                    "commit_pipeline_depth must be an int >= 0 (0 = off, "
                    "N = an N-step speculative window) or 'auto'; got "
                    f"{raw_depth!r}"
                ) from None
            if self._commit_pipeline_depth < 0:
                raise ValueError(
                    "commit_pipeline_depth must be an int >= 0 (0 = off, "
                    "N = an N-step speculative window) or 'auto'; got "
                    f"{self._commit_pipeline_depth}"
                )
        # Adaptive-controller observations (EWMAs over the pipelined loop's
        # reports; see observe_pipeline_step / _adapt_pipeline_depth).
        self._pipeline_interval_ewma: Optional[float] = None
        self._pipeline_stall_ewma: Optional[float] = None
        self._barrier_rtt_ewma: Optional[float] = None
        self._pipeline_last_obs: Optional[float] = None
        self._pipeline_obs_count = 0
        # Trial bookkeeping: a deepen is an experiment — (old depth, old
        # per-step interval) to judge it against; _adapt_hold freezes the
        # controller after a deepen that did not pay, until the next era.
        self._adapt_trial_from: Optional[tuple] = None
        self._adapt_hold = False
        # Speculative-vote pool (depth >= 2 / adaptive): the barrier RPCs
        # for the window's steps must overlap ON THE WIRE, which the
        # single-thread quorum executor cannot do. Lazily created.
        self._commit_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._commit_pool_lock = threading.Lock()
        self._use_async_quorum = use_async_quorum
        self._replica_world_size_mode = world_size_mode
        self._init_sync = init_sync
        self._max_retries = max_retries

        self._group_rank: int = (
            group_rank if group_rank is not None else int(os.environ.get("GROUP_RANK", "0"))
        )
        self._group_world_size: int = (
            group_world_size
            if group_world_size is not None
            else int(os.environ.get("GROUP_WORLD_SIZE", "1"))
        )

        # Gray-failure health plane (torchft_tpu/health.py): explicit
        # monitor injection (drills/bench) or env-gated auto-attach
        # ($TPUFT_HEALTH=1). The quarantine gate runs NOW — before the
        # ManagerServer below starts heartbeating — so a replica whose
        # previous incarnation self-ejected must pass its accelerator
        # self-probe (with exponential backoff; crash-loop parking)
        # before it re-enters anyone's quorum view. Rejoin then rides
        # the normal heal path (delta rejoin makes the comeback cheap).
        self._health: Optional[health_plane.HealthMonitor] = health_monitor
        if self._health is None and health_plane.enabled():
            self._health = health_plane.HealthMonitor(
                replica_id=(replica_id or "replica"),
                group_rank=self._group_rank,
                min_replica_size=min_replica_size,
            )
        if self._health is not None:
            self._health.bind(min_replica_size=min_replica_size)
            self._health.serve_quarantine_if_pending()

        self._store = store
        # The default heal transport speaks the heal wire class: its
        # stages encode with $TPUFT_HEAL_CODEC (default fp32 = bit-for-bit
        # the pre-codec format) and a joiner decodes after CRC/digest
        # verification — decode failures funnel into report_error through
        # the same HealIntegrityError path as any corrupt donor.
        self._checkpoint_transport: CheckpointTransport = (
            checkpoint_transport
            if checkpoint_transport is not None
            else HTTPTransport(timeout=self._timeout, wire="heal")
        )
        # Serving-plane failures (e.g. a heal-serve sidecar crash,
        # TPUFT_HEAL_SERVE_MODE=child) funnel into report_error: the step
        # does not commit and the supervisor-visible error log carries the
        # crash — the train loop itself never observes it.
        self._checkpoint_transport.register_error_callback(self.report_error)

        # State-dict function registry under a readers-writer lock: readers
        # are checkpoint serves, the writer is the optimizer step
        # (reference: manager.py:229, :341-366).
        self._state_dict_lock = RWLock()
        self._load_state_dict_fns: Dict[str, Callable[[Any], None]] = {}
        self._user_state_dicts: Dict[str, Callable[[], Any]] = {}
        if load_state_dict is not None and state_dict is not None:
            self.register_state_dict_fn("default", load_state_dict, state_dict)

        # Step/commit accounting.
        self._step = 0
        self._batches_committed = 0
        self._commit_failures = 0

        # Versioned weight history (torchft_tpu/history.py): the ring of
        # committed state refs the optimizer promotes into at commit
        # resolution. Sized off the commit-pipeline window by default —
        # depth+1 versions are exactly what the rollback ring already
        # held, so a deep-window donor can serve quorum.max_step EXACTLY
        # after a drain advanced its live step past it (the PR-9
        # "fail cleanly and retry" round becomes an immediate serve).
        # TPUFT_HISTORY_MAX_VERSIONS / TPUFT_HISTORY_BYTES override.
        window = (
            self._adaptive_max_depth
            if self._commit_pipeline_adaptive
            else self._commit_pipeline_depth
        )
        self._history = WeightHistory(max_versions=max(1, int(window)) + 1)

        # Per-step error/heal state.
        self._errored: Optional[ExceptionWithTraceback] = None
        self._shutdown_hooks: List[Callable[[], None]] = []
        self._quorum_change_hooks: List[Callable[[], None]] = []
        self._heal_parts_filters: List[Callable[[], Any]] = []
        # Serving plane (torchft_tpu/serving): commit-tail publish hooks
        # (cheap due-marks) + the attached publisher the step boundary
        # publishes through — see register_publish_hook/_maybe_publish.
        self._publish_hooks: List[Callable[[int, int], None]] = []
        self._publisher: Optional[Any] = None
        self._publisher_state_fn: Optional[Callable[[], Any]] = None
        self._healing = False
        self._pending_state_dict: Optional[Dict[str, Any]] = None
        self._pending_commit_future: Optional[_TrackedCommitFuture] = None

        # Heal failover accounting (spans quorum rounds; reset on a heal
        # that lands): consecutive failed attempts, the donor that failed
        # last (for the failover counter), and per-donor one-shot
        # fail-fast skips (addr -> skip_pending).
        self._heal_max_attempts = max(
            1, int(os.environ.get(HEAL_MAX_ATTEMPTS_ENV, str(heal_max_attempts)))
        )
        self._heal_attempts = 0
        self._heal_last_failed_donor: Optional[str] = None
        self._heal_failed_donors: Dict[str, bool] = {}
        # Advisory per-donor identity map for the CURRENT heal attempt
        # (donor url -> {"replica_id", "region"}); rebuilt by
        # _resolve_stripe_donors each attempt.
        self._heal_donor_info: Dict[str, Dict[str, Any]] = {}

        # Quorum state.
        self._quorum_id = -1
        self._quorum_future: Optional[concurrent.futures.Future] = None
        self._participating_replica_rank: Optional[int] = None
        self._participating_replica_world_size: int = 0
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpuft_quorum"
        )

        # Rank 0 embeds the native ManagerServer; other local ranks discover
        # its address through the group store (reference: manager.py:293-325).
        self._manager: Optional[ManagerServer] = None
        hostname = hostname or socket.gethostname()
        if self._group_rank == 0:
            lighthouse = lighthouse_addr or os.environ.get(LIGHTHOUSE_ENV)
            if lighthouse is None:
                raise ValueError(
                    f"rank 0 requires lighthouse_addr or ${LIGHTHOUSE_ENV}"
                )
            bind = manager_bind
            port_env = os.environ.get(MANAGER_PORT_ENV)
            if port_env is not None and bind == "[::]:0":
                bind = f"[::]:{port_env}"
            replica_id = (replica_id or "") + ":" + str(uuid.uuid4())
            self._manager = ManagerServer(
                replica_id=replica_id,
                lighthouse_addr=lighthouse,
                address=hostname,
                bind=bind,
                store_addr=store_addr,
                world_size=self._group_world_size,
                heartbeat_interval=heartbeat_interval,
                connect_timeout=self._connect_timeout,
                quorum_retries=self._quorum_retries,
            )
            self._store.set("manager_addr", self._manager.address().encode())
            self._store.set("replica_id", replica_id.encode())

        addr = self._store.get("manager_addr", timeout=self._connect_timeout)
        assert addr is not None
        replica_id_bytes = self._store.get("replica_id", timeout=self._connect_timeout)
        assert replica_id_bytes is not None
        self._replica_id = replica_id_bytes.decode()
        # WAN topology: register who this process is with the emulated-link
        # shim (a no-op without a configured topology) so wire seams can
        # resolve the local region from the replica-id map.
        netem.set_local_replica_id(self._replica_id)
        self._client = ManagerClient(addr.decode(), connect_timeout=self._connect_timeout)

        self._logger = _ManagerLogger(self, self._replica_id, self._group_rank)

        # Fleet metrics: every phase counter/histogram below is labeled with
        # the STABLE replica id (the user prefix, without the per-process
        # uuid suffix) so counters accumulate across supervised restarts of
        # the same replica group — the operator-facing identity.
        self._metric_labels = {
            "replica_id": self._replica_id.split(":", 1)[0] or "replica",
            "group_rank": str(self._group_rank),
        }
        self._metrics_push_interval = metrics.push_interval_sec()
        self._metrics_last_push = 0.0
        metrics.maybe_start_http_server()
        metrics.set_gauge(
            "tpuft_pipeline_depth",
            self._commit_pipeline_depth,
            **self._metric_labels,
        )

        # Trace plane: this manager's journal is whatever journal is
        # current on the CONSTRUCTING thread (threads-as-replicas drills
        # install one per replica thread; real processes get the process
        # default), captured here so events recorded from the quorum
        # thread still land in this replica's journal. Identity uses the
        # stable replica id — restarts of the same group continue one
        # timeline, exactly like the metric labels.
        self._trace = tracing.current()
        self._trace.configure(
            job_id=os.environ.get("JOB_ID", "unknown"),
            replica_id=self._metric_labels["replica_id"],
            group_rank=self._group_rank,
        )
        self._trace.set_step(self._step, self._quorum_id)
        self._trace_clock = tracing.StoreClockSampler(
            self._trace,
            owner_key=f"{self._metric_labels['replica_id']}/{self._group_rank}",
            claim=self._group_rank == 0,
        )
        # Goodput ledger: a fold over this replica's trace ring, closed on
        # the metrics-push cadence; its payload rides the metrics snapshot
        # so fleet_status/goodput_report can account fleet wall-clock
        # without journal access. SLO burn-rate alerting (TPUFT_SLO_*)
        # lives inside the ledger — alerting only, never actuation.
        self._goodput = goodput_plane.GoodputLedger(
            journal=self._trace, labels=self._metric_labels
        )

        # Health plane wiring that needs the full identity: the monitor
        # journals into this replica's timeline and funnels wedge-path
        # errors through report_error like every other comm-layer error.
        if self._health is not None:
            self._health.bind(trace=self._trace, report_error=self.report_error)
            self.register_shutdown_hook(self._health.stop)

    # ------------------------------------------------------------------
    # state dict registry
    # ------------------------------------------------------------------

    def register_state_dict_fn(
        self,
        key: str,
        load_state_dict: Callable[[T], None],
        state_dict: Callable[[], T],
    ) -> None:
        assert key not in self._load_state_dict_fns, f"duplicate state dict key {key}"
        self._load_state_dict_fns[key] = cast(Callable[[Any], None], load_state_dict)
        self._user_state_dicts[key] = state_dict

    def disallow_state_dict_read(self, timeout: Optional[float] = None) -> None:
        """Takes the state-dict write lock: blocks checkpoint serves while the
        optimizer mutates registered state (reference: allow/disallow pair
        used by LocalSGD/DiLoCo step hooks, local_sgd.py:112-128)."""
        effective = self._timeout if timeout is None else timeout
        if not self._state_dict_lock.w_acquire(effective):
            raise TimeoutError("state dict write lock not acquired")

    def allow_state_dict_read(self) -> None:
        self._state_dict_lock.w_release()

    @property
    def commit_pipeline_depth(self) -> int:
        """How many uncommitted steps the train loop may keep in flight
        (0 = resolve every commit before the next dispatch). In adaptive
        mode this is the CURRENT depth — the controller moves it between 1
        and the adaptive ceiling as the measured RTT/step ratio changes;
        the pipelined step_fn re-reads it every call."""
        return self._commit_pipeline_depth

    @property
    def commit_pipeline_adaptive(self) -> bool:
        return self._commit_pipeline_adaptive

    # ------------------------------------------------------------------
    # adaptive depth controller
    # ------------------------------------------------------------------

    _ADAPT_EVERY_OBS = 4  # re-evaluate cadence, in pipelined-step reports
    _EWMA_ALPHA = 0.3

    def _ewma(self, prev: Optional[float], value: float) -> float:
        if prev is None:
            return value
        return prev + self._EWMA_ALPHA * (value - prev)

    def observe_pipeline_step(self, stall_s: float) -> None:
        """Per-resolution report from the pipelined step loop: ``stall_s``
        is how long the train thread sat blocked on this step's verdict +
        device bound (the serialized latency the window failed to hide).
        Feeds the adaptive controller's EWMAs; every few reports the
        controller runs one trial-and-judge round:

        - measurable stall remaining -> DEEPEN one slot as a trial;
        - at the next round, keep the deepen only if the per-step wall
          actually improved (>= 5%) — stall that deepening cannot remove
          (a compute-throughput backlog looks exactly like an unhidden
          round trip from the train thread) reverts the trial and holds
          the controller until the next quorum era.

        Shrinking below a kept depth happens only at era boundaries
        (:meth:`_adapt_pipeline_depth`), so a noisy fast step cannot
        oscillate the window against a slow link."""
        now = time.monotonic()
        if self._pipeline_last_obs is not None:
            self._pipeline_interval_ewma = self._ewma(
                self._pipeline_interval_ewma, now - self._pipeline_last_obs
            )
        self._pipeline_last_obs = now
        self._pipeline_stall_ewma = self._ewma(
            self._pipeline_stall_ewma, max(stall_s, 0.0)
        )
        self._pipeline_obs_count += 1
        if not self._commit_pipeline_adaptive:
            return
        if self._pipeline_obs_count % self._ADAPT_EVERY_OBS:
            return
        interval = self._pipeline_interval_ewma or 0.0
        stall = self._pipeline_stall_ewma or 0.0
        if interval <= 0.0:
            return
        if self._adapt_trial_from is not None:
            prev_depth, prev_interval = self._adapt_trial_from
            self._adapt_trial_from = None
            if interval >= 0.95 * prev_interval:
                # The deepen did not pay: revert and hold this era.
                self._adapt_hold = True
                self._set_pipeline_depth(prev_depth)
                return
        if self._adapt_hold:
            return
        if (
            stall > 0.15 * interval
            and self._commit_pipeline_depth < self._adaptive_max_depth
        ):
            self._adapt_trial_from = (self._commit_pipeline_depth, interval)
            self._set_pipeline_depth(self._commit_pipeline_depth + 1)

    def _adapt_pipeline_depth(self) -> None:
        """Quorum-era re-evaluation (called on a quorum_id change, after
        the drain hooks emptied the window): clear any hold/trial and
        re-derive the depth from the measured control-plane RTT vs step
        time — ``ceil(barrier_rtt / step_compute)`` where step_compute is
        the inter-step interval minus the observed stall (what the loop
        spends NOT waiting on verdicts). This is where the window can
        SHRINK; a link that degrades mid-era deepens it through
        :meth:`observe_pipeline_step`'s trial rounds instead of stalling
        the fleet."""
        if not self._commit_pipeline_adaptive:
            return
        self._adapt_trial_from = None
        self._adapt_hold = False
        rtt = self._barrier_rtt_ewma
        interval = self._pipeline_interval_ewma
        if rtt is None or interval is None:
            return  # no evidence yet: keep the current depth
        compute = max(interval - (self._pipeline_stall_ewma or 0.0), 1e-4)
        ideal = int(math.ceil(rtt / compute))
        self._set_pipeline_depth(max(1, min(ideal, self._adaptive_max_depth)))

    def _set_pipeline_depth(self, depth: int) -> None:
        if depth == self._commit_pipeline_depth:
            return
        self._logger.info(
            f"adaptive commit pipeline: depth {self._commit_pipeline_depth} "
            f"-> {depth} (barrier_rtt={self._barrier_rtt_ewma}, "
            f"interval={self._pipeline_interval_ewma}, "
            f"stall={self._pipeline_stall_ewma})"
        )
        self._commit_pipeline_depth = depth
        # Re-measure under the new depth: stall/interval evidence gathered
        # at the old depth would keep re-triggering the deepen rule after
        # the window already absorbed the latency (observed as runaway
        # deepening at RTT 0). The barrier-RTT EWMA stays — the wire's
        # round trip is depth-independent.
        self._pipeline_stall_ewma = None
        self._pipeline_interval_ewma = None
        self._pipeline_last_obs = None
        metrics.set_gauge("tpuft_pipeline_depth", depth, **self._metric_labels)
        self._trace.record(
            "pipeline_depth", step=self._step, quorum_id=self._quorum_id,
            depth=depth,
        )

    @property
    def history(self) -> WeightHistory:
        """The step-labeled ring of committed state refs (history.py):
        state owners (the optimizer) promote each committed step here at
        commit RESOLUTION — never from a live speculative window — and
        the donor staging path consults it so a joiner asking for
        ``quorum.max_step`` is served that exact committed step even
        when this donor's window drained past it."""
        return self._history

    def _history_state_dict(self, step: int) -> Optional[Dict[str, Any]]:
        """The exact manager-shaped state dict for committed ``step``
        from the history ring, or None when it cannot be served exactly
        (evicted, a registered key never promoted — e.g. DiLoCo's
        fragments, which don't promote yet — or accounting missing).
        None means the caller stages its drained step instead: the
        fallback fetches more, it never mislabels."""
        if not self._user_state_dicts:
            return None
        return self._history.state_dict_at(step, set(self._user_state_dicts))

    def register_quorum_change_hook(self, hook: Callable[[], None]) -> None:
        """Runs ``hook`` on the quorum thread whenever the quorum id
        changes, BEFORE the process group reconfigures (and therefore
        before any donor checkpoint send for the new quorum).

        This is the pipelined-commit drain point: a membership change must
        not reconfigure the comm layer — or stage a donor send — while an
        uncommitted speculative step is still in flight, so the pipelined
        optimizer registers a full pipeline resolution here. Hook errors
        funnel into :meth:`report_error` (the step will not commit) rather
        than aborting the reconfigure."""
        self._quorum_change_hooks.append(hook)

    def _run_quorum_drain_hooks(self) -> None:
        """Runs the registered quorum-change (speculative-window drain)
        hooks on the calling (quorum) thread. Idempotent by contract —
        every registered hook resolves records in place — so it runs on a
        quorum-id change AND again before any donor send, making "no
        ``pg.configure`` / ``send_checkpoint`` inside an undrained window"
        structural (tpuft_check rule R7 pins the ordering lexically).
        Hook errors funnel into :meth:`report_error` (the step will not
        commit) rather than aborting the reconfigure or the serve."""
        schedules.point("manager.quorum_drain_hooks")
        for hook in self._quorum_change_hooks:
            try:
                hook()
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"quorum-change drain hook failed: {e}")
                self.report_error(e)

    def register_publish_hook(self, hook: Callable[[int, int], None]) -> None:
        """Runs ``hook(committed_step, quorum_id)`` after every committed
        step's accounting (both the inline ``should_commit`` tail and the
        speculative window's deferred resolution). Hooks must be CHEAP —
        they run on the commit-resolution path — and must not sample
        state: a depth-N pipeline's live state contains younger
        speculative steps at resolution time. The serving plane's
        publisher registers a due-mark here; the actual state capture
        happens at the next step boundary (:meth:`_maybe_publish`), after
        a full window drain. Hook errors are logged and dropped — the
        serving plane must never poison a commit."""
        self._publish_hooks.append(hook)

    def attach_publisher(
        self, publisher: Any, state_fn: Optional[Callable[[], Any]] = None
    ) -> None:
        """Attaches a ``serving.WeightPublisher``: commits mark it due via
        :meth:`register_publish_hook`, and the step boundary publishes
        through :meth:`_maybe_publish`. ``state_fn`` samples the state to
        publish (e.g. ``lambda: opt.params``); default is the registered
        user state dicts. The publisher's serving-sidecar failures funnel
        into :meth:`report_error` like the heal transport's."""
        self._publisher = publisher
        self._publisher_state_fn = state_fn
        publisher.register_error_callback(self.report_error)
        self.register_publish_hook(publisher.note_commit)
        self.register_shutdown_hook(lambda: publisher.shutdown(wait=False))

    def _run_publish_hooks(self, step: int, quorum_id: int) -> None:
        for hook in self._publish_hooks:
            try:
                hook(step, quorum_id)
            except Exception:  # noqa: BLE001 — serving must never wound a commit
                self._logger.exception("publish hook failed (ignored)")

    def _maybe_publish(self) -> None:
        """The publication site, run on the train thread at the step
        boundary (:meth:`start_quorum`) when the attached publisher has a
        version due. The speculative window is drained FIRST — identical
        discipline to donor sends, pinned lexically by analyzer rule R7 —
        so published bytes are always committed-only; the state sample
        rides the state-dict read lock like a checkpoint serve. Failures
        are counted and logged (serving lags; training is unaffected)."""
        publisher = self._publisher
        if publisher is None:
            return
        if publisher.due():
            schedules.point("manager.maybe_publish")
            try:
                # Publication must never sample speculative-window state:
                # resolve the full window before touching params (R7).
                self._run_quorum_drain_hooks()
                with self._state_dict_lock.r_lock(timeout=self._timeout):
                    if self._publisher_state_fn is not None:
                        state = self._publisher_state_fn()
                    else:
                        state = {
                            key: fn() for key, fn in self._user_state_dicts.items()
                        }
                with metrics.timer(
                    "tpuft_publish_seconds", **self._metric_labels
                ), self._trace.span(
                    "publish", step=self._step, quorum_id=self._quorum_id
                ):
                    publisher.publish(
                        step=self._step, quorum_id=self._quorum_id, state=state
                    )
            except Exception as e:  # noqa: BLE001 — publication is best-effort
                metrics.inc("tpuft_publish_failures_total", **self._metric_labels)
                self._logger.exception(
                    f"publish failed (readers lag one cadence; training "
                    f"unaffected): {e}"
                )
        # Progressive delivery: one rollout-verdict evidence window per
        # STEP BOUNDARY, not per publication — a canary wave must keep
        # accumulating evidence between publishes or a slow cadence would
        # starve the verdict loop (serving/rollout.py RolloutDirector).
        # on_commit never raises — verdicts are advisory to the step loop.
        director = getattr(publisher, "rollout_director", None)
        if director is not None:
            director.on_commit(self._step, self._quorum_id)

    def register_heal_parts_filter(self, fn: Callable[[], Any]) -> None:
        """Registers a callable returning the set of heal-part names
        (``checkpointing.transport.HEAL_PART_PREFIX`` keys) this replica
        does NOT need a donor to stream — it reconstructs them through a
        cheaper plane instead (the ZeRO optimizer re-balances its shard
        states from survivors over the PG). The union of all filters is
        passed to ``recv_checkpoint(skip_parts=...)`` on every heal;
        filter errors are ignored (skipping is an optimization — the
        fallback is simply fetching everything)."""
        self._heal_parts_filters.append(fn)

    def _heal_skip_parts(self) -> Optional[set]:
        skip: set = set()
        for fn in self._heal_parts_filters:
            try:
                skip |= set(fn() or ())
            except Exception:  # noqa: BLE001 — skip is best-effort
                self._logger.exception("heal parts filter failed (ignored)")
        return skip or None

    def register_shutdown_hook(self, hook: Callable[[], None]) -> None:
        """Runs ``hook`` during :meth:`shutdown` (before the executor stops).

        Lets higher layers tie per-manager resources (e.g. ddp's cached fp8
        wire worker) to the manager's explicit lifecycle instead of garbage
        collection — a shut-down manager held by a fixture list must not
        leak threads. Hooks run at most once; errors are swallowed so one
        failing hook cannot block teardown."""
        self._shutdown_hooks.append(hook)

    def shutdown(self, wait: bool = True) -> None:
        hooks, self._shutdown_hooks = self._shutdown_hooks, []
        for hook in hooks:
            try:
                hook()
            except Exception:
                pass
        self._checkpoint_transport.shutdown(wait=wait)
        if self._manager is not None:
            self._manager.shutdown()
        self._executor.shutdown(wait=wait)
        with self._commit_pool_lock:
            if self._commit_pool is not None:
                self._commit_pool.shutdown(wait=wait)
                self._commit_pool = None
        self._client.close()

    # ------------------------------------------------------------------
    # allreduce
    # ------------------------------------------------------------------

    def allreduce(
        self,
        tensor: Any,
        should_quantize: bool = False,
        reduce_op: ReduceOp = ReduceOp.AVG,
    ) -> Work:
        """Fault-tolerant allreduce (reference: manager.py:385-467).

        Stages ``tensor`` to host, averages it across participating replica
        groups, and returns a :class:`Work` resolving to the result (numpy).
        On error the work resolves to the *input* tensor and the error is
        tracked via :meth:`errored` — the step will not commit.

        AVG runs as SUM + divide by ``num_participants()`` so the math is
        world-size independent; non-participating replicas contribute zeros.
        """
        if self.errored():
            return _DummyWork(tensor)

        with trace_span("tpuft::manager::allreduce", step=self._step):
            return self._allreduce_impl(tensor, should_quantize, reduce_op)

    def _allreduce_impl(
        self, tensor: Any, should_quantize: bool, reduce_op: ReduceOp
    ) -> Work:
        self.wait_quorum()
        num_participants = self.num_participants()

        array = np.asarray(tensor)
        if not self.is_participating():
            array = np.zeros_like(array)

        pg_reduce_op = reduce_op
        if reduce_op == ReduceOp.AVG:
            # kind "V" covers ml_dtypes custom floats (bfloat16, fp8).
            if array.dtype.kind not in ("f", "V"):
                raise ValueError("average reduce op requires floating point tensors")
            pg_reduce_op = ReduceOp.SUM

        try:
            if should_quantize:
                from torchft_tpu.parallel.collectives import allreduce_quantized

                work = allreduce_quantized([array], pg_reduce_op, self._pg)
            else:
                work = self._pg.allreduce([array], pg_reduce_op)

            def callback(result: List[np.ndarray]) -> np.ndarray:
                out = result[0]
                if reduce_op == ReduceOp.AVG:
                    out = (out / num_participants).astype(out.dtype)
                return out

            return self.wrap_work(work.then(callback), default=array)
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"allreduce failed; poisoning this step (commit will be skipped): {e}")
            self.report_error(e)
            return _DummyWork(tensor)

    def allreduce_pytree(self, pytree: Any, should_quantize: bool = False) -> Work:
        """Averages every array leaf of ``pytree`` across replicas; resolves
        to a pytree of the same structure (numpy leaves).

        Leaves are **bucketed**: same-dtype leaves concatenate into one flat
        buffer per dtype so the wire carries one collective per bucket
        instead of one per parameter (DDP's frozen-bucket role; flatten
        order is deterministic across replicas for identical models)."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(pytree)
        # Same contract as the scalar `allreduce` AVG path (see
        # _allreduce_impl): averaging integer leaves would silently
        # floor-divide. Validate BEFORE every early return (errored /
        # lone-replica) so the programming error surfaces deterministically
        # at any quorum size instead of only once a second replica joins.
        for leaf in leaves:
            if np.dtype(getattr(leaf, "dtype", type(leaf))).kind not in ("f", "V"):
                raise ValueError(
                    "allreduce_pytree averages leaves and requires floating "
                    f"point dtypes; got {np.dtype(getattr(leaf, 'dtype', type(leaf)))}. "
                    "Cast the leaf to float or exclude it from the synced pytree."
                )
        if self.errored():
            return _DummyWork(pytree)
        with trace_span("tpuft::manager::allreduce_pytree", step=self._step):
            self.wait_quorum()
            num_participants = self.num_participants()
            if self.is_lone_replica():
                # Identity: SUM over one participant / 1. Resolve to host
                # copies of the leaves (the documented numpy contract)
                # without touching the wire.
                return _DummyWork(
                    jax.tree_util.tree_unflatten(
                        treedef, [np.asarray(leaf) for leaf in leaves]
                    )
                )
            # Launch every device→host copy before completing any: the
            # per-leaf np.asarray then drains transfers that are already in
            # flight instead of serializing them.
            prefetch_to_host(leaves)
            arrays = [np.asarray(leaf) for leaf in leaves]
        if not self.is_participating():
            arrays = [np.zeros_like(a) for a in arrays]

        # Bucket same-dtype leaves (stable order). The quantized path stays
        # per-leaf: concatenation would let one fp8 block's max-abs scale
        # span parameter boundaries and crush small-magnitude leaves to 0.
        if should_quantize:
            buckets: Dict[Any, List[int]] = {
                index: [index] for index in range(len(arrays))
            }
            flat_buffers = [a.reshape(-1) for a in arrays]
        else:
            buckets = {}
            for index, array in enumerate(arrays):
                buckets.setdefault(array.dtype, []).append(index)
            flat_buffers = [
                np.concatenate([arrays[i].reshape(-1) for i in members])
                if len(members) > 1
                else arrays[members[0]].reshape(-1)
                for members in buckets.values()
            ]
        try:
            if should_quantize:
                from torchft_tpu.parallel.collectives import allreduce_quantized

                work = allreduce_quantized(flat_buffers, ReduceOp.SUM, self._pg)
            else:
                work = self._pg.allreduce(flat_buffers, ReduceOp.SUM)

            def callback(result: List[np.ndarray]) -> Any:
                averaged: List[Any] = [None] * len(arrays)
                for flat, members in zip(result, buckets.values()):
                    # Float-only by the precondition above.
                    flat = (flat / num_participants).astype(flat.dtype)
                    offset = 0
                    for i in members:
                        size = arrays[i].size
                        # Copy: leaves must not alias one shared bucket
                        # buffer (or the caller's input via an echo PG).
                        averaged[i] = (
                            flat[offset : offset + size]
                            .reshape(arrays[i].shape)
                            .copy()
                        )
                        offset += size
                return jax.tree_util.tree_unflatten(treedef, averaged)

            return self.wrap_work(work.then(callback), default=pytree)
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"allreduce failed; poisoning this step (commit will be skipped): {e}")
            self.report_error(e)
            return _DummyWork(pytree)

    def allreduce_prequantized(self, payload: Any, scales: Any) -> Work:
        """Averages device-prequantized data (fp8 payload + f32 block scales,
        ops/quantization.py layout) across participating replicas with the
        same semantics as :meth:`allreduce_pytree`: non-participants zero
        their contribution (by zeroing scales — free), errors resolve the
        work to None and poison the step. Resolves to (payload, scales) of
        the average for device-side dequantization."""
        from torchft_tpu.parallel.collectives import allreduce_quantized_wire

        if self.errored():
            return _DummyWork(None)
        with trace_span("tpuft::manager::allreduce_prequantized"):
            self.wait_quorum()
            num_participants = self.num_participants()
        if self.is_lone_replica():
            # Averaging over one participant is the identity: skip the
            # device→host→wire→device round trip entirely (the payload stays
            # on device; callers feed it straight back to the dequant jit).
            return self.wrap_work(_DummyWork((payload, scales)), default=None)
        if not self.is_participating():
            scales = scales * 0
        try:
            work = allreduce_quantized_wire(payload, scales, ReduceOp.SUM, self._pg)
            return self.wrap_work(
                work.then(lambda ps: (ps[0], ps[1] / max(num_participants, 1))),
                default=None,
            )
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"allreduce failed; poisoning this step (commit will be skipped): {e}")
            self.report_error(e)
            return _DummyWork(None)

    # ------------------------------------------------------------------
    # error tracking
    # ------------------------------------------------------------------

    def report_error(self, e: Exception) -> None:
        """Records an error for this step: the step will not commit and the
        comm layer is reconfigured on the next quorum."""
        self._errored = ExceptionWithTraceback(e)
        metrics.inc("tpuft_errors_total", **self._metric_labels)
        self._trace.record(
            "report_error",
            step=self._step,
            quorum_id=self._quorum_id,
            error=str(e),
            error_type=type(e).__name__,
        )
        errors_logger.info(
            "error",
            extra={
                "job_id": os.environ.get("JOB_ID", "unknown"),
                "replica_id": self._replica_id,
                "rank": self._group_rank,
                "quorum_id": self._quorum_id,
                "step": self._step,
                "error": str(e),
            },
        )
        from torchft_tpu.utils import flight_recorder

        flight_recorder.dump_on_failure(
            "manager",
            f"report_error step={self._step} quorum={self._quorum_id}: {e}",
        )

    def errored(self) -> Optional[ExceptionWithTraceback]:
        return self._errored

    def wrap_work(self, work: Work, default: Any, timeout: Optional[float] = None) -> Work:
        """Bounds ``work`` with a deadline and swallows its errors into
        :meth:`report_error`, resolving to ``default`` instead (reference
        ``wrap_future``, manager.py:491-532)."""
        from torchft_tpu.futures import future_timeout

        timed = Work(future_timeout(work._future, timeout or self._timeout))

        def handler(e: Exception) -> None:
            self._logger.exception(f"future raised; remaining callbacks skipped: {e}")
            self.report_error(e)

        return timed.with_error_handler(handler, default)

    # Alias matching the reference name.
    wrap_future = wrap_work

    # ------------------------------------------------------------------
    # quorum
    # ------------------------------------------------------------------

    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: Optional[float] = None,
    ) -> None:
        """Starts a (possibly async) quorum and readies the manager for a new
        step (reference: manager.py:534-589). Call before the forward pass."""
        schedules.point("manager.start_quorum")
        if self._quorum_future is not None:
            self._quorum_future.result()

        # Enforce the should_commit_async ordering contract: the commit
        # barrier reads (and may heal through) the per-step error/heal flags,
        # so an unresolved commit future queued behind this quorum would vote
        # with wiped flags and silently drop a pending heal. Drain it here so
        # the misordering is impossible rather than merely documented.
        self._drain_pending_commit("start_quorum")

        # Gray-failure self-ejection: a latched degraded verdict (or a
        # wedge-watchdog trip) leaves the fleet HERE, at the step
        # boundary, with the previous commit fully resolved — the same
        # supervisor-escalation family as a quorum timeout or
        # HealExhaustedError. Survivors observe an ordinary membership
        # change (window drain -> pg.configure -> proceed) and this
        # replica rejoins through the quarantine gate + normal heal path.
        if self._health is not None:
            eject_reason = self._health.should_eject()
            if eject_reason is not None:
                err = DegradedReplicaError(eject_reason)
                self.report_error(err)
                self._health.note_ejected(eject_reason)
                raise err

        self._errored = None
        self._healing = False

        # Serving plane: a due publication runs here, on the train thread,
        # with no quorum task in flight — the drain inside can resolve the
        # window's votes without racing the quorum executor, and any error
        # it reports sticks to THIS step's freshly wiped flags.
        self._maybe_publish()

        self._quorum_future = self._executor.submit(
            self._async_quorum,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
            quorum_timeout=timeout or self._quorum_timeout,
        )
        if not self._use_async_quorum:
            self.wait_quorum()
            if self._healing:
                # Eagerly apply the pending state dict so the forward pass
                # runs against recovered parameters.
                self._apply_pending_state_dict()
                self._healing = False

    def _drain_pending_commit(self, caller: str) -> None:
        """Resolves any should_commit_async future the caller never
        observed, BEFORE the per-step error/heal flags are wiped (or a new
        barrier queued behind it): an unresolved commit queued behind a new
        quorum would vote with wiped flags and silently drop a pending
        heal, and a stored barrier exception (e.g. the max_retries
        RuntimeError, the supervisor-restart signal) must propagate rather
        than be silently dropped. A future the caller already resolved and
        handled is NOT replayed on a later, healthy step."""
        pending_commit = self._pending_commit_future
        self._pending_commit_future = None
        if pending_commit is not None and not pending_commit.consumed:
            if not pending_commit.done():
                self._logger.warn(
                    f"{caller} called with an unresolved should_commit_async "
                    "future; draining it so the commit votes with its own "
                    "step's error/heal flags instead of the wiped ones"
                )
            pending_commit.result()

    def wait_quorum(self) -> None:
        """Blocks until the quorum completes; the PG is healthy after."""
        assert self._quorum_future is not None, "must call start_quorum before wait_quorum"
        with trace_span("tpuft::manager::wait_quorum", step=self._step):
            self._quorum_future.result()

    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: float
    ) -> None:
        try:
            with trace_span(
                "tpuft::manager::_client::_quorum", step=self._step
            ), metrics.timer(
                "tpuft_quorum_seconds", **self._metric_labels
            ), self._trace.span("quorum", step=self._step):
                quorum = self._client._quorum(
                    group_rank=self._group_rank,
                    step=self._step,
                    checkpoint_metadata=self._checkpoint_transport.metadata(),
                    shrink_only=shrink_only,
                    init_sync=self._init_sync,
                    commit_failures=self._commit_failures,
                    timeout=quorum_timeout,
                )
        except Exception as e:
            # A quorum that never resolves is supervisor-restart territory
            # (the exception escalates out of the quorum future): stamp the
            # shared incident id so every process that timed out on the
            # same quorum dumps a correlatable journal + flight-recorder
            # ring under $TPUFT_FLIGHT_RECORDER.
            kind = (
                "quorum_timeout"
                if isinstance(e, TimeoutError) or "timed out" in str(e).lower()
                else "quorum_error"
            )
            tracing.open_incident(
                kind, self._step, self._quorum_id,
                journal=self._trace, reason=str(e),
            )
            raise

        # Participation bookkeeping: async quorum means a healing replica
        # sits out this step (max-step cohort participates); sync quorum
        # means everyone participates post-heal (reference: manager.py:
        # 636-657).
        if self._use_async_quorum or not allow_heal:
            self._participating_replica_rank = quorum.max_rank
            self._participating_replica_world_size = quorum.max_world_size
        else:
            self._participating_replica_rank = quorum.replica_rank
            self._participating_replica_world_size = quorum.replica_world_size

        if self._replica_world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            self._participating_replica_world_size = min(
                self._participating_replica_world_size, self._min_replica_size
            )
            if (
                self._participating_replica_rank is not None
                and self._participating_replica_rank >= self._min_replica_size
            ):
                self._participating_replica_rank = None

        metrics.set_gauge(
            "tpuft_participants",
            self._participating_replica_world_size,
            **self._metric_labels,
        )
        # Storm visibility: how many members of this quorum are behind
        # max_step (i.e. joining/healing) as THIS replica observed it.
        # Pushed with the metrics snapshot, so fleet_status's JOINERS
        # column shows every replica's view — drift between views is
        # itself a debugging signal (a member seeing stale quorums).
        joining = 0
        if quorum.quorum is not None and quorum.max_step > 0:
            joining = sum(
                1
                for member in quorum.quorum.participants
                if member.step < quorum.max_step
            )
        metrics.set_gauge(
            "tpuft_heal_storm_joiners", joining, **self._metric_labels
        )
        if self._health is not None:
            # Peer discovery for the health board: participant ids + the
            # quorum's shared rendezvous store. Best-effort inside.
            self._health.on_quorum(quorum)
        self._trace.record(
            "quorum_ready",
            step=self._step,
            quorum_id=quorum.quorum_id,
            participants=self._participating_replica_world_size,
            heal=bool(quorum.heal),
            joining=joining,
        )

        if quorum.quorum_id != self._quorum_id:
            metrics.inc("tpuft_quorum_changes_total", **self._metric_labels)
            self._trace.record(
                "quorum_change",
                step=self._step,
                quorum_id=quorum.quorum_id,
                old_quorum_id=self._quorum_id,
                participants=self._participating_replica_world_size,
            )
            quorums_logger.info(
                "quorum",
                extra={
                    "job_id": os.environ.get("JOB_ID", "unknown"),
                    "replica_id": self._replica_id,
                    "rank": self._group_rank,
                    "quorum_id": quorum.quorum_id,
                    "step": quorum.max_step,
                },
            )
            store_prefixed_addr = (
                f"{quorum.store_address}/tpuft/{quorum.quorum_id}/{self._group_rank}"
            )
            self._logger.info(
                f"reconfiguring for quorum_id={quorum.quorum_id} {store_prefixed_addr=}"
            )
            # Membership changed: drain anything the pipelined-commit mode
            # still has in flight BEFORE reconfiguring the wire or serving
            # a donor checkpoint — the new quorum era (and any joiner
            # healing from this replica) must observe committed state only.
            # With a depth-N window this resolves the FULL window; the
            # committed step may advance past quorum.max_step here, and
            # the donor send below then serves max_step EXACTLY from the
            # history ring (resolved slots promote instead of dropping —
            # torchft_tpu/history.py). Only a ring miss falls back to
            # staging the drained step honestly labeled, which the joiner
            # rejects cleanly and retries — never mislabeled bytes.
            self._run_quorum_drain_hooks()
            # Era boundary: the adaptive controller re-derives its depth
            # from the measured barrier RTT vs step time (the only point
            # the window may SHRINK — see _adapt_pipeline_depth).
            self._adapt_pipeline_depth()
            try:
                with trace_span(
                    "tpuft::manager::_pg::configure",
                    quorum_id=quorum.quorum_id,
                    step=self._step,
                ), metrics.timer(
                    "tpuft_pg_configure_seconds", **self._metric_labels
                ), self._trace.span(
                    "pg_configure", step=self._step, quorum_id=quorum.quorum_id
                ):
                    self._pg.configure(
                        store_prefixed_addr,
                        self._replica_id,
                        quorum.replica_rank,
                        quorum.replica_world_size,
                    )
                metrics.inc("tpuft_pg_configure_total", **self._metric_labels)
                self._quorum_id = quorum.quorum_id
                self._trace.set_step(self._step, self._quorum_id)
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"got exception in pg configure: {e}")
                self.report_error(e)
                return

        if allow_heal:
            # Striped heals fetch from EVERY max-step member, not only the
            # assigned donor: when a heal is in flight anywhere in the
            # quorum, each member whose state matches max_step co-stages
            # the same committed bytes so joiners can partition the fetch
            # across the whole donor set. The digest is donor-independent
            # (bitwise-identical committed state), which is what makes the
            # co-staged copies interchangeable.
            stripe_costage = (
                heal_stripe_enabled()
                and not quorum.recover_dst_replica_ranks
                and quorum.max_step > 0
                and self._step == quorum.max_step
                and not quorum.heal
                and quorum.quorum is not None
                and any(
                    member.step < quorum.max_step
                    for member in quorum.quorum.participants
                )
            )
            if quorum.recover_dst_replica_ranks or stripe_costage:
                # A donor send must NEVER sample speculative state, even
                # when the quorum id did not move (e.g. a repeated heal
                # round inside one era): drain the full window here too —
                # idempotent, the membership-change path above already ran
                # the hooks when the id changed. In child serve mode the
                # sidecar's restaged snapshot therefore can never contain
                # uncommitted state either.
                self._run_quorum_drain_hooks()
                serve_step = quorum.max_step
                serve_state_dict: Optional[Dict[str, Any]] = None
                if self._step > serve_step:
                    # Draining a depth-N window advanced our committed
                    # step past the quorum's (pre-drain-reported)
                    # max_step. The history ring holds the last K
                    # committed steps exactly (optim promotes each slot
                    # at resolution), so serve the joiner the step it
                    # asked for — the committed bytes AT max_step,
                    # honestly labeled. Only a ring miss (evicted /
                    # never promoted) falls back to staging the drained
                    # step, which the joiner rejects cleanly and retries
                    # next round — never mislabeled bytes either way.
                    # Step 0 is the init_sync mosaic (per-rank state,
                    # never history-served).
                    if serve_step > 0:
                        serve_state_dict = self._history_state_dict(serve_step)
                    if serve_state_dict is not None:
                        metrics.inc(
                            "tpuft_history_exact_serves_total",
                            **self._metric_labels,
                        )
                        self._trace.record(
                            "history_exact_serve",
                            step=serve_step,
                            quorum_id=quorum.quorum_id,
                            drained_step=self._step,
                        )
                        self._logger.info(
                            f"donor serving step {serve_step} exactly from "
                            f"the history ring (drained step {self._step})"
                        )
                    else:
                        metrics.inc(
                            "tpuft_history_misses_total",
                            **self._metric_labels,
                        )
                        self._logger.info(
                            f"donor staging drained step {self._step} "
                            f"(quorum max_step={serve_step}): history ring "
                            "cannot serve the exact step"
                        )
                        serve_step = self._step
                try:
                    if stripe_costage:
                        self._logger.info(
                            "a peer is healing; co-staging our checkpoint "
                            "for the striped donor set"
                        )
                        metrics.inc(
                            "tpuft_heal_stripe_costages_total",
                            **self._metric_labels,
                        )
                    else:
                        self._logger.info(
                            f"peers need recovery from us {quorum.recover_dst_replica_ranks}"
                        )
                        metrics.inc(
                            "tpuft_heals_total",
                            role="donor",
                            **self._metric_labels,
                        )
                    with trace_span(
                        "tpuft::manager::_checkpoint_transport::send_checkpoint",
                        quorum_id=quorum.quorum_id,
                        step=serve_step,
                    ), metrics.timer(
                        "tpuft_heal_send_seconds", **self._metric_labels
                    ), self._trace.span(
                        "heal_send",
                        step=serve_step,
                        quorum_id=quorum.quorum_id,
                        dst_ranks=str(list(quorum.recover_dst_replica_ranks)),
                    ):
                        self._checkpoint_transport.send_checkpoint(
                            dst_ranks=quorum.recover_dst_replica_ranks,
                            step=serve_step,
                            state_dict=(
                                serve_state_dict
                                if serve_state_dict is not None
                                else self._manager_state_dict()
                            ),
                            timeout=self._timeout,
                            quorum_id=quorum.quorum_id,
                        )
                except Exception as e:  # noqa: BLE001
                    self._logger.exception(f"got exception in donor send: {e}")
                    self.report_error(e)

            if quorum.heal:
                self._heal_as_joiner(quorum)

    def _heal_as_joiner(self, quorum: Any) -> None:
        """One heal attempt against the quorum's donor set, with the
        failover accounting around it.

        The assigned donor stays the anchor (its /meta is fetched first,
        and the single-donor path is byte-identical to the pre-striping
        behavior), but the transfer itself stripes across every max-step
        participant the quorum advertises (:meth:`_resolve_stripe_donors`)
        and diffs against the local stale state when there is one
        (:meth:`_delta_local_state`) — donor death/stall/staleness inside
        the stripe set is handled *inside* the attempt by reassignment.
        Only when the whole attempt fails does the cross-round machinery
        here engage: the failure funnels into :meth:`report_error` (clean
        fail — the joiner re-enters the next quorum still joining and the
        transport's per-chunk resume cache keeps the verified chunks), the
        donor is marked for a one-shot fail-fast skip (a dead donor also
        leaves via heartbeat expiry, so the next assignment excludes it),
        and once ``heal_max_attempts`` consecutive attempts have failed
        :class:`HealExhaustedError` escalates out of the quorum future to
        the supervisor."""
        self._healing = True
        metrics.set_gauge("tpuft_healing", 1, **self._metric_labels)
        metrics.inc("tpuft_heals_total", role="joiner", **self._metric_labels)
        src_addr = quorum.recover_src_manager_address
        try:
            if self._heal_attempts > 0:
                metrics.inc("tpuft_heal_retries_total", **self._metric_labels)
            if self._heal_failed_donors.get(src_addr, False):
                # One-shot fail-fast: this donor failed us on the previous
                # attempt; skip the transfer (no window burned against
                # fresh evidence) so the next quorum round can rotate the
                # assignment. If it assigns the same donor again, attempt
                # it for real — it may have recovered.
                self._heal_failed_donors[src_addr] = False
                raise _DonorRecentlyFailed(
                    f"donor {src_addr} failed the previous heal attempt; "
                    "skipping one round to let the assignment rotate"
                )
            if (
                self._heal_last_failed_donor is not None
                and src_addr != self._heal_last_failed_donor
            ):
                metrics.inc(
                    "tpuft_heal_donor_failovers_total", **self._metric_labels
                )
                self._logger.info(
                    f"heal failover: donor {self._heal_last_failed_donor} "
                    f"failed, retrying from {src_addr}"
                )
            self._logger.info(
                "healing required, fetching checkpoint metadata from "
                f"{src_addr} max_step={quorum.max_step}"
            )
            primary_client = ManagerClient(
                src_addr,
                connect_timeout=self._connect_timeout,
            )
            checkpoint_metadata = primary_client._checkpoint_metadata(
                self._group_rank, timeout=self._timeout
            )
            primary_client.close()
            assert (
                quorum.recover_src_replica_rank is not None
            ), "must have a recover rank when healing"
            rotation = self._storm_rotation(quorum)
            metrics.set_gauge(
                "tpuft_heal_storm_rotation", rotation, **self._metric_labels
            )
            donor_urls = self._resolve_stripe_donors(quorum, rotation=rotation)
            # The assigned donor rides the same advisory info map (its
            # replica id comes from the quorum view by address) so the
            # transport's bandwidth EWMA and same-/cross-region byte
            # accounting cover the anchor donor too.
            q = quorum.quorum
            if q is not None:
                for member in q.participants:
                    if member.address == src_addr:
                        self._heal_donor_info[checkpoint_metadata] = {
                            "replica_id": member.replica_id,
                            "region": netem.region_of(member.replica_id),
                        }
                        break
            local_state = self._delta_local_state(quorum)
            with trace_span(
                "tpuft::manager::_checkpoint_transport::recv_checkpoint",
                quorum_id=quorum.quorum_id,
                step=quorum.max_step,
            ), metrics.timer(
                "tpuft_heal_recv_seconds", **self._metric_labels
            ), self._trace.span(
                "heal_recv",
                step=quorum.max_step,
                quorum_id=quorum.quorum_id,
                donor=src_addr,
                donors=len(donor_urls) + 1,
                delta=local_state is not None,
                attempt=self._heal_attempts,
                rotation=rotation,
            ):
                self._pending_state_dict = self._checkpoint_transport.recv_checkpoint(
                    src_rank=quorum.recover_src_replica_rank,
                    metadata=checkpoint_metadata,
                    step=quorum.max_step,
                    timeout=self._timeout,
                    quorum_id=quorum.quorum_id,
                    skip_parts=self._heal_skip_parts(),
                    donors=donor_urls,
                    local_state=local_state,
                    stripe_rotation=rotation,
                    donor_info=self._heal_donor_info,
                )
            # Restore manager accounting immediately; user state is
            # applied from the main thread when safe.
            self.load_state_dict(self._pending_state_dict["tpuft"])
            self._step = quorum.max_step
            self._trace.set_step(self._step)
            self._heal_attempts = 0
            self._heal_last_failed_donor = None
            self._heal_failed_donors.clear()
        except Exception as e:  # noqa: BLE001
            if not isinstance(e, _DonorRecentlyFailed):
                self._heal_attempts += 1
                self._heal_last_failed_donor = src_addr
                self._heal_failed_donors[src_addr] = True
            self._logger.exception(f"got exception in recovery: {e}")
            self._trace.record(
                "heal_attempt_failed",
                step=quorum.max_step,
                quorum_id=quorum.quorum_id,
                donor=src_addr,
                attempt=self._heal_attempts,
                error=str(e),
            )
            self.report_error(e)
            if self._heal_attempts >= self._heal_max_attempts:
                tracing.open_incident(
                    "heal_exhausted", quorum.max_step, quorum.quorum_id,
                    journal=self._trace,
                    reason=f"{self._heal_attempts} attempts, last donor {src_addr}",
                )
                raise HealExhaustedError(
                    f"{self._heal_attempts} consecutive heal attempts failed "
                    f"(last donor {src_addr}); escalating to the supervisor "
                    f"(bound from ${HEAL_MAX_ATTEMPTS_ENV})"
                ) from e

    def _storm_rotation(self, quorum: Any) -> int:
        """This joiner's coordinated-storm offset (see
        :func:`storm_stripe_rotation`): derived purely from the quorum
        view every member already holds, so N joiners agree on who is
        joiner 0..N-1 without a single extra RPC."""
        joining: List[str] = []
        q = quorum.quorum
        if q is not None and quorum.max_step > 0:
            joining = [
                member.replica_id
                for member in q.participants
                if member.step < quorum.max_step
            ]
        return storm_stripe_rotation(
            self._replica_id, joining, self._group_rank, quorum.quorum_id
        )

    def _resolve_stripe_donors(
        self, quorum: Any, rotation: Optional[int] = None
    ) -> List[str]:
        """Extra donor addresses for a striped heal: every quorum
        participant standing at ``max_step`` holds bitwise-identical
        committed state (and co-stages it when it sees a joiner — see
        ``_async_quorum``), so its transport can serve any stripe of the
        fetch. Each candidate's manager resolves to its checkpoint
        transport address; resolution is best-effort per donor — a peer
        that cannot be resolved is simply left out of the stripe set,
        never a reason to fail the heal. The extras rotate by the storm
        offset (:meth:`_storm_rotation` — joiner ordinal + group rank +
        quorum id) so N concurrent joiners spread their donor ORDER and,
        past the stripe cap, their donor SUBSETS across the fleet
        instead of all hammering it in the same sequence.

        Striping is skipped entirely at ``max_step == 0``: the init_sync
        heal is a per-LOCAL-rank mosaic (state is intentionally NOT
        identical across replicas yet), so only the assigned donor is
        valid there.

        Under a WAN topology (``netem.topology_enabled``) the rotated
        candidate order is stably re-sorted same-region-first BEFORE the
        cap, so the stripe set saturates the cheap intra-region links and
        cross-region donors only fill remaining slots; a region with zero
        live same-region donors keeps its cross-region candidates — the
        preference can narrow where bytes come from, never whether they
        come. With no topology the sort key is uniform and the order (and
        behavior) is byte-identical to the region-blind plan."""
        self._heal_donor_info = {}
        if not heal_stripe_enabled() or quorum.max_step <= 0:
            return []
        q = quorum.quorum
        if q is None:
            return []
        candidates = [
            (member.address, member.replica_id)
            for member in q.participants
            if member.address
            and member.address != quorum.recover_src_manager_address
            and member.replica_id != self._replica_id
            and member.step >= quorum.max_step
        ]
        if not candidates:
            return []
        if rotation is None:
            rotation = self._storm_rotation(quorum)
        # Rotate BEFORE capping: joiners beyond the cap then resolve
        # different donor subsets, not just different orderings.
        rotate = rotation % len(candidates)
        candidates = candidates[rotate:] + candidates[:rotate]
        my_region = netem.local_region()
        if my_region is not None:
            # Stable: within each region class the storm rotation's
            # ordering survives, so concurrent joiners still spread.
            candidates.sort(
                key=lambda c: 0 if netem.region_of(c[1]) == my_region else 1
            )
        # The cap minus the assigned donor; the transport re-applies it
        # after deduping, this just avoids pointless resolution RPCs.
        candidates = candidates[: max(0, heal_stripe_max_donors() - 1)]
        urls: List[str] = []
        for addr, rid in candidates:
            try:
                client = ManagerClient(
                    addr, connect_timeout=self._connect_timeout
                )
                try:
                    url = client._checkpoint_metadata(
                        self._group_rank, timeout=self._timeout
                    )
                finally:
                    client.close()
                urls.append(url)
                self._heal_donor_info[url] = {
                    "replica_id": rid,
                    "region": netem.region_of(rid),
                }
            except Exception as e:  # noqa: BLE001 — best-effort per donor
                self._logger.warn(
                    f"stripe donor {addr} metadata resolution failed ({e}); "
                    "striping without it"
                )
        metrics.set_gauge(
            "tpuft_heal_stripe_donors", len(urls) + 1, **self._metric_labels
        )
        return urls

    def _delta_local_state(self, quorum: Any) -> Optional[Dict[str, Any]]:
        """The joiner's stale-but-recent state for delta rejoin, or None
        when there is nothing worth diffing: delta disabled, no real local
        progress (``step == 0`` — freshly initialized state, and the
        init_sync mosaic owns step-0 heals anyway), or no registered user
        state yet. Building it costs one host snapshot; the transport pays
        one serialize+CRC pass only after the donor's manifest proves the
        layouts comparable."""
        if not heal_delta_enabled() or self._step <= 0 or quorum.max_step <= 0:
            return None
        if not self._user_state_dicts:
            return None
        try:
            return self._manager_state_dict()
        except Exception as e:  # noqa: BLE001 — delta is an optimization
            self._logger.warn(
                f"delta-rejoin local state unavailable ({e}); full fetch"
            )
            return None

    def _apply_pending_state_dict(self) -> None:
        schedules.point("manager.apply_pending_state")
        assert self._healing, "must be in healing state"
        assert self._quorum_future is not None, "must call start_quorum first"
        self._quorum_future.result()

        if self._pending_state_dict is None:
            assert self.errored(), "checkpoint was not staged and no error occurred"
            return
        self._logger.info("applying pending state dict")
        assert self._load_state_dict_fns, "user load_state_dict is not initialized"
        pending_user = cast(Dict[str, Any], self._pending_state_dict["user"])
        # Healing rebinds registered state: take the writer so a checkpoint
        # serve staging on another thread never captures a half-applied
        # mosaic (the lock-discipline invariant R3 enforces statically —
        # the load fns themselves are suppressed at their definition sites
        # because THIS caller owns the lock).
        self.disallow_state_dict_read()
        try:
            for key, load_fn in self._load_state_dict_fns.items():
                load_fn(pending_user[key])
        finally:
            self.allow_state_dict_read()
        self._pending_state_dict = None
        metrics.set_gauge("tpuft_healing", 0, **self._metric_labels)
        self._logger.info("Loaded state dict.")

    # ------------------------------------------------------------------
    # commit
    # ------------------------------------------------------------------

    def should_commit_async(
        self, timeout: Optional[float] = None
    ) -> "_TrackedCommitFuture":
        """:meth:`should_commit` dispatched on the manager's executor so the
        barrier RPC overlaps work the caller still has to do this step —
        e.g. dispatching the speculative optimizer update (optim.py) or the
        next batch's h2d. The reference's analogue is keeping commit cost
        off the step's critical path (manager.py:790-878 design note).

        The caller SHOULD resolve the future before reading any state the
        barrier may heal (should_commit applies pending state dicts) and
        before calling start_quorum. The ordering is enforced:
        ``start_quorum`` drains any still-unresolved commit future before
        wiping the per-step error/heal flags, so a misordered caller blocks
        (and sees the barrier's exception, if any) instead of silently
        dropping a pending heal."""
        # A second async barrier with the first still unobserved would
        # silently drop the first's tracking (and any stored exception) on
        # overwrite — drain it with the same semantics start_quorum uses.
        self._drain_pending_commit("should_commit_async")
        future = _TrackedCommitFuture(self._executor.submit(self.should_commit, timeout))
        self._pending_commit_future = future
        return future

    def should_commit(self, timeout: Optional[float] = None) -> bool:
        """All-local-rank commit barrier (reference: manager.py:790-878).

        Call after the step's math is complete (``jax.block_until_ready`` on
        the outputs) and step the optimizer only when this returns True.
        """
        # The barrier must run unlocked: it may apply a healing state dict
        # (write lock) and peer serve threads need the read lock meanwhile.
        # No-op unless the lock-order detector is enabled (TPUFT_LOCK_CHECK).
        lockcheck.check_barrier("Manager.should_commit")
        if err := self._pg.errored():
            self.report_error(err)

        if self._healing:
            self._apply_pending_state_dict()

        enough_replicas = self.num_participants() >= self._min_replica_size
        local_should_commit = enough_replicas and self._errored is None
        self._trace.record(
            "vote_send",
            step=self._step,
            quorum_id=self._quorum_id,
            vote=local_should_commit,
            enough_replicas=enough_replicas,
            errored=self._errored is not None,
        )
        barrier_t0 = time.perf_counter()
        with trace_span(
            "tpuft::manager::should_commit",
            step=self._step,
            quorum_id=self._quorum_id,
        ), metrics.timer(
            "tpuft_commit_barrier_seconds", **self._metric_labels
        ), self._trace.span(
            "commit_barrier",
            step=self._step,
            quorum_id=self._quorum_id,
            vote=local_should_commit,
        ):
            should_commit = self._client.should_commit(
                self._group_rank,
                self._step,
                local_should_commit,
                timeout=timeout or self._timeout,
            )
        # The barrier releases every local rank together, so the rank that
        # entered LAST waited LEAST — fleet_status derives its STRAGGLER/
        # LAG column from this gauge across the pushed snapshots, and
        # fleet_trace uses the barrier-release instant as its fine clock
        # anchor.
        metrics.set_gauge(
            "tpuft_trace_barrier_wait_seconds",
            time.perf_counter() - barrier_t0,
            **self._metric_labels,
        )
        self._logger.info(
            f"should_commit={should_commit} enough_replicas={enough_replicas}, "
            f"errored={self._errored}"
        )
        commits_logger.info(
            "commit",
            extra={
                "job_id": os.environ.get("JOB_ID", "unknown"),
                "replica_id": self._replica_id,
                "rank": self._group_rank,
                "quorum_id": self._quorum_id,
                "step": self._step,
                "commit_result": should_commit,
            },
        )

        self._checkpoint_transport.disallow_checkpoint()

        if should_commit:
            self._trace.record(
                "commit", step=self._step, quorum_id=self._quorum_id
            )
            self._step += 1
            self._batches_committed += self.num_participants()
            self._commit_failures = 0
            # History-ring accounting for this committed step (cheap
            # ints, never a state sample): the state half arrives from
            # the optimizer's promotion at adoption.
            self._history.note_accounting(self._step, self._batches_committed)
            metrics.inc("tpuft_commits_total", **self._metric_labels)
            metrics.set_gauge(
                "tpuft_last_commit_time", time.time(), **self._metric_labels
            )
            self._run_publish_hooks(self._step, self._quorum_id)
            # A committed step closes any open incident window: later dumps
            # get fresh ids instead of riding a resolved incident.
            tracing.clear_incident(self._trace)
        else:
            self._commit_failures += 1
            metrics.inc("tpuft_commit_failures_total", **self._metric_labels)
            self._trace.record(
                "commit_failed",
                step=self._step,
                quorum_id=self._quorum_id,
                consecutive_failures=self._commit_failures,
            )
        self._trace.set_step(self._step, self._quorum_id)
        metrics.set_gauge("tpuft_step", self._step, **self._metric_labels)
        metrics.set_gauge(
            "tpuft_batches_committed", self._batches_committed, **self._metric_labels
        )
        self._push_metrics()
        if self._health is not None:
            # One health-scoring window per commit resolution (cheap,
            # never raises): watchdog beat, rollup ingest, board
            # push/pull, verdict latching. Actuation waits for the next
            # start_quorum — the step boundary.
            self._health.on_step(
                self._step,
                committed=should_commit,
                participants=self._participating_replica_world_size,
            )
        if not should_commit:
            if self._max_retries is not None and self._commit_failures > self._max_retries:
                msg = (
                    f"should_commit failed {self._commit_failures} times consecutively, "
                    f"exceeding max_retries={self._max_retries}"
                )
                self._logger.exception(msg)
                raise RuntimeError(msg)
        return should_commit

    # ------------------------------------------------------------------
    # speculative commits (the depth-N pipelined window)
    # ------------------------------------------------------------------

    def speculative_commit_async(
        self, claimed_step: int, timeout: Optional[float] = None
    ) -> _SpeculativeCommitFuture:
        """Commit-barrier vote for the speculative step ``claimed_step``
        (committed step + window offset) — the depth>=2 / adaptive vote
        path of the pipelined commit schedule.

        Split-phase ``should_commit``: the LOCAL phase (pg error read,
        pending-heal apply, vote computation) runs here on the caller
        thread, so the vote reflects exactly this step's error/heal flags
        before the next ``start_quorum`` wipes them — the property
        ``_drain_pending_commit`` enforces by blocking on the depth<=1
        path. The barrier RPC rides the commit pool so every window
        slot's vote overlaps on the wire, and the step/batch accounting
        defers to the first ``result()`` delivery (the pipelined
        optimizer resolves oldest-first, keeping accounting in step
        order; see :class:`_SpeculativeCommitFuture`).
        ``should_commit_async`` remains the depth<=1 path: its
        quorum-executor FIFO ordering is what the depth-1 tests pin."""
        lockcheck.check_barrier("Manager.speculative_commit_async")
        if err := self._pg.errored():
            self.report_error(err)
        if self._healing:
            self._apply_pending_state_dict()
        participants = self.num_participants()
        enough_replicas = participants >= self._min_replica_size
        local_should_commit = enough_replicas and self._errored is None
        self._trace.record(
            "vote_send",
            step=claimed_step,
            quorum_id=self._quorum_id,
            vote=local_should_commit,
            enough_replicas=enough_replicas,
            errored=self._errored is not None,
            speculative=True,
        )
        inner = self._commit_executor().submit(
            self._speculative_barrier, claimed_step, local_should_commit, timeout
        )
        return _SpeculativeCommitFuture(
            self, inner, claimed_step, local_should_commit, participants
        )

    def _commit_executor(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._commit_pool_lock:
            if self._commit_pool is None:
                depth_bound = (
                    self._adaptive_max_depth
                    if self._commit_pipeline_adaptive
                    else self._commit_pipeline_depth
                )
                self._commit_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=max(2, min(int(depth_bound), 16)),
                    thread_name_prefix="tpuft_commit",
                )
            return self._commit_pool

    def _speculative_barrier(
        self, step: int, vote: bool, timeout: Optional[float]
    ) -> bool:
        """The barrier RPC leg of one speculative vote (commit-pool
        thread). Also the adaptive controller's RTT sensor: measured here
        the barrier round trip is UNHIDDEN, unlike the stall the train
        thread observes once the window covers it."""
        barrier_t0 = time.perf_counter()
        try:
            with trace_span(
                "tpuft::manager::speculative_commit",
                step=step,
                quorum_id=self._quorum_id,
            ), metrics.timer(
                "tpuft_commit_barrier_seconds", **self._metric_labels
            ), self._trace.span(
                "commit_barrier", step=step, quorum_id=self._quorum_id, vote=vote
            ):
                return self._client.should_commit(
                    self._group_rank, step, vote, timeout=timeout or self._timeout
                )
        finally:
            elapsed = time.perf_counter() - barrier_t0
            self._barrier_rtt_ewma = self._ewma(self._barrier_rtt_ewma, elapsed)
            metrics.set_gauge(
                "tpuft_trace_barrier_wait_seconds", elapsed, **self._metric_labels
            )

    def _speculative_commit_resolved(
        self, step: int, should_commit: bool, participants: int
    ) -> None:
        """Deferred accounting tail of one speculative vote (mirrors
        :meth:`should_commit`'s inline tail), applied in window order on
        the consuming thread. ``participants`` was captured at vote
        launch — re-reading it here could block on the CURRENT quorum
        future from the quorum thread itself (the drain hook runs inside
        ``_async_quorum``)."""
        self._logger.info(
            f"speculative should_commit={should_commit} step={step} "
            f"errored={self._errored}"
        )
        commits_logger.info(
            "commit",
            extra={
                "job_id": os.environ.get("JOB_ID", "unknown"),
                "replica_id": self._replica_id,
                "rank": self._group_rank,
                "quorum_id": self._quorum_id,
                "step": step,
                "commit_result": should_commit,
            },
        )
        self._checkpoint_transport.disallow_checkpoint()
        if should_commit:
            self._trace.record("commit", step=step, quorum_id=self._quorum_id)
            if step != self._step:
                # Resolution is oldest-first by construction; a mismatch
                # means the owner broke window order — keep accounting
                # monotone and loud rather than silently double-counting.
                self._logger.warn(
                    f"speculative commit for step {step} resolved at "
                    f"committed step {self._step} (window order violated?)"
                )
            self._step = max(self._step, step + 1)
            self._batches_committed += participants
            self._commit_failures = 0
            self._history.note_accounting(self._step, self._batches_committed)
            metrics.inc("tpuft_commits_total", **self._metric_labels)
            metrics.set_gauge(
                "tpuft_last_commit_time", time.time(), **self._metric_labels
            )
            self._run_publish_hooks(self._step, self._quorum_id)
            tracing.clear_incident(self._trace)
        else:
            self._commit_failures += 1
            metrics.inc("tpuft_commit_failures_total", **self._metric_labels)
            self._trace.record(
                "commit_failed",
                step=step,
                quorum_id=self._quorum_id,
                consecutive_failures=self._commit_failures,
            )
        self._trace.set_step(self._step, self._quorum_id)
        metrics.set_gauge("tpuft_step", self._step, **self._metric_labels)
        metrics.set_gauge(
            "tpuft_batches_committed", self._batches_committed, **self._metric_labels
        )
        self._push_metrics()
        if self._health is not None:
            # Same per-resolution health window as the inline tail;
            # participants were captured at vote launch (re-reading here
            # could block on the current quorum future).
            self._health.on_step(
                self._step, committed=should_commit, participants=participants
            )
        if not should_commit:
            if self._max_retries is not None and self._commit_failures > self._max_retries:
                msg = (
                    f"should_commit failed {self._commit_failures} times consecutively, "
                    f"exceeding max_retries={self._max_retries}"
                )
                self._logger.exception(msg)
                raise RuntimeError(msg)

    # ------------------------------------------------------------------
    # metrics push (the fleet-table feed)
    # ------------------------------------------------------------------

    def _push_metrics(self, force: bool = False) -> None:
        """Publishes this process's metrics snapshot into the group store
        under ``metrics/<replica_id>/<group_rank>`` (rate-limited by
        ``$TPUFT_METRICS_PUSH_SEC``). The replica id key is the FULL id
        (uuid included) — exactly what the lighthouse status reports for
        this group — so ``scripts/fleet_status.py`` can join lighthouse
        members to their snapshots without a key-listing RPC the store
        does not have. Best-effort: a push failure never poisons a step."""
        interval = self._metrics_push_interval
        if interval <= 0:
            return
        now = time.monotonic()
        if not force and now - self._metrics_last_push < interval:
            return
        self._metrics_last_push = now
        try:
            payload = json.dumps(
                {
                    "ts": time.time(),
                    "replica_id": self._replica_id,
                    "group_rank": self._group_rank,
                    "step": self._step,
                    "batches_committed": self._batches_committed,
                    "healing": self._healing,
                    # WAN topology: this replica's region (None without a
                    # configured topology) — feeds fleet_status's REGION
                    # column; a string, so it rides the snapshot top level
                    # rather than the numeric metrics registry.
                    "region": netem.local_region(),
                    "metrics": metrics.snapshot(),
                    # Goodput accounting: closing a due ledger window here
                    # also scores the SLO — both ride this push cadence.
                    "goodput": self._goodput.collect(
                        step=self._step, quorum_id=self._quorum_id
                    ),
                }
            ).encode()
            self._store.set(
                f"metrics/{self._replica_id}/{self._group_rank}", payload
            )
        except Exception as e:  # noqa: BLE001 — observability must not wound
            self._logger.warn(f"metrics push failed (ignored): {e}")
        self._push_trace()

    def _push_trace(self) -> None:
        """Publishes this process's journal segment (events since the last
        push) plus its per-step phase rollup into the group store under
        ``trace/<replica_id>/<group_rank>``, and runs one clock-beacon
        sampling round — both riding the metrics-push cadence. The rollup
        feeds fleet_status's STRAGGLER/LAG column; the segments (and the
        fuller ``/trace.json`` surface) feed scripts/fleet_trace.py.
        Best-effort: a push failure never poisons a step."""
        try:
            segment = self._trace.drain_segment()
            payload = json.dumps(
                {
                    "ts": time.time(),
                    "replica_id": self._replica_id,
                    "group_rank": self._group_rank,
                    "job_id": self._trace.job_id,
                    "wall": time.time(),
                    "mono": time.monotonic(),
                    "clock_offset_s": self._trace.clock_offset_s,
                    "events": segment,
                    "phases": self._trace.phase_rollup(),
                }
            ).encode()
            self._store.set(
                f"{tracing.STORE_PREFIX}/{self._replica_id}/{self._group_rank}",
                payload,
            )
            self._trace_clock.tick(self._store)
        except Exception as e:  # noqa: BLE001 — observability must not wound
            self._logger.warn(f"trace push failed (ignored): {e}")

    # ------------------------------------------------------------------
    # state dict / accounting
    # ------------------------------------------------------------------

    def load_state_dict(self, state_dict: Dict[str, int]) -> None:
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]
        # A checkpoint restore rewrote the step counter: resident history
        # entries' step labels no longer describe this trajectory.
        self._history.clear()

    def _manager_state_dict(self) -> Dict[str, Any]:
        with self._state_dict_lock.r_lock(timeout=self._timeout):
            assert self._user_state_dicts, "user state_dict is not initialized"
            return {
                "user": {key: fn() for key, fn in self._user_state_dicts.items()},
                "tpuft": self.state_dict(),
            }

    def state_dict(self) -> Dict[str, int]:
        """Manager accounting for user checkpoints: persist alongside model
        state and restore via :meth:`load_state_dict`."""
        return {"step": self._step, "batches_committed": self._batches_committed}

    def current_step(self) -> int:
        return self._step

    def batches_committed(self) -> int:
        return self._batches_committed

    def participating_rank(self) -> Optional[int]:
        if self._quorum_future is None:
            return None
        self.wait_quorum()
        return self._participating_replica_rank

    def num_participants(self) -> int:
        if self._quorum_future is None:
            return 0
        self.wait_quorum()
        assert self._participating_replica_world_size >= 0, "internal error"
        return self._participating_replica_world_size

    def is_lone_replica(self) -> bool:
        """True when this replica is ALONE on the wire for the current
        quorum: sole participant AND a process-group world of one. Then
        every averaging collective is an exact identity (SUM over one,
        divided by one) and may skip the stage/wire round trip.

        Both conditions matter: a healing joiner is a PG member without
        being a participant, and if the survivor skipped the wire while the
        joiner entered the collective, the joiner would average with nobody
        and replica states would diverge (caught by the kill-recovery
        bitwise-equality integ tests)."""
        return (
            self.num_participants() == 1
            and self.is_participating()
            and self._pg.size() <= 1
        )

    def is_participating(self) -> bool:
        if self._participating_replica_rank is None:
            return False
        if self._healing:
            assert self._use_async_quorum
            return False
        return True


class _ManagerLogger:
    def __init__(self, manager: Manager, replica_id: str, group_rank: int) -> None:
        self._logger = logging.getLogger("torchft_tpu.manager")
        self._replica_id = replica_id
        self._group_rank = group_rank
        self._manager = manager

    def _prefix(self) -> str:
        return f"[{self._replica_id}/{self._group_rank} - step {self._manager.current_step()}]"

    def info(self, msg: str) -> None:
        self._logger.info(f"{self._prefix()} {msg}")

    def warn(self, msg: str) -> None:
        self._logger.warning(f"{self._prefix()} {msg}")

    def exception(self, msg: str) -> None:
        self._logger.exception(f"{self._prefix()} {msg}")
