"""Fleet metrics plane: dependency-free counters, gauges, and histograms.

The reference leans on external sinks for aggregate observability (its OTel
hookup, otel.py, exports raw event records and leaves aggregation to a
collector). This stack runs where neither prometheus_client nor an OTel
collector can be assumed, so the registry here is self-contained stdlib:
every FT phase (quorum wait, wire allreduce, device sync, vote RTT, heal
transfer) lands in process-local metrics that export on three surfaces —

- ``prometheus_text()``: the Prometheus exposition format, served by
  :func:`start_http_server` (``$TPUFT_METRICS_PORT``) and by the
  checkpoint transport's HTTP server at ``GET /metrics``;
- ``snapshot()``: a JSON-safe dict; ``bench.py`` merges it into its one
  JSON line as ``ft_phase_*`` fields, the flight recorder appends it as a
  dump trailer, and each Manager pushes it into its group store under
  ``metrics/<replica_id>/<group_rank>`` for ``scripts/fleet_status.py``;
- direct reads: :func:`counter_total` / :func:`histogram_stats` for tests
  and the ft_harness counter assertions.

Metric identity is ``(name, sorted label items)``; get-or-create accessors
return the same live object for the same identity, and every mutation takes
the metric's own lock so concurrent increments from the op-worker, quorum,
and train-loop threads never lose updates. The canonical metric names and
label sets are tabulated in METRICS.md — a drift test greps the tree and
diffs against that table, so new metrics must be registered there.

Env: ``TPUFT_METRICS_PORT`` (serve /metrics on this port; 0 = ephemeral),
``TPUFT_METRICS_PUSH_SEC`` (min seconds between store pushes, default 10;
<= 0 disables the push).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Generator, Iterable, List, Optional, Set, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "WindowedSeries",
    "DEFAULT_TIME_BUCKETS",
    "DEFAULT_BYTES_PER_SEC_BUCKETS",
    "ENV_PORT",
    "ENV_PUSH_SEC",
    "counter",
    "gauge",
    "histogram",
    "inc",
    "set_gauge",
    "observe",
    "timer",
    "snapshot",
    "snapshot_to_prometheus",
    "prometheus_text",
    "counter_total",
    "gauge_value",
    "histogram_stats",
    "start_http_server",
    "maybe_start_http_server",
]

ENV_PORT = "TPUFT_METRICS_PORT"
ENV_PUSH_SEC = "TPUFT_METRICS_PUSH_SEC"

# Seconds-scale phases span ~100 us (acked-buffer readiness probes) to the
# 60 s RPC timeout ceiling; edges follow the Prometheus 1-2.5-5 ladder.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Byte-rate phases (heal stream throughput): a fenced gray donor drips at
# ~100 B/s, a healthy DCN heal runs at GB/s — same 1-2.5-5 ladder.
DEFAULT_BYTES_PER_SEC_BUCKETS: Tuple[float, ...] = (
    1e2, 2.5e2, 5e2, 1e3, 2.5e3, 5e3, 1e4, 2.5e4, 5e4, 1e5, 2.5e5, 5e5,
    1e6, 2.5e6, 5e6, 1e7, 2.5e7, 5e7, 1e8, 2.5e8, 5e8, 1e9, 2.5e9, 5e9,
)

LabelItems = Tuple[Tuple[str, str], ...]


def _label_items(labels: Dict[str, Any]) -> LabelItems:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(items: LabelItems) -> str:
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def _fmt(value: float) -> str:
    # Integral values print as integers so counter lines stay diff-stable.
    if float(value).is_integer() and abs(value) < 2**53:
        return str(int(value))
    return repr(float(value))


class Counter:
    """Monotonically increasing float; negative increments are rejected."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError(f"counter increment must be >= 0, got {value}")
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus ``le`` semantics: a bucket counts
    observations <= its edge; ``+Inf`` counts everything). Bounded memory:
    one int per edge, no per-observation storage."""

    __slots__ = ("_lock", "edges", "_bucket_counts", "_sum", "_count")

    def __init__(self, buckets: Iterable[float] = DEFAULT_TIME_BUCKETS) -> None:
        edges = tuple(sorted(float(b) for b in buckets))
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        self._lock = threading.Lock()
        self.edges = edges
        self._bucket_counts = [0] * len(edges)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, edge in enumerate(self.edges):
                if value <= edge:
                    self._bucket_counts[i] += 1
                    break

    def stats(self) -> Dict[str, Any]:
        """{"sum", "count", "mean", "buckets"}: buckets are CUMULATIVE
        counts keyed by edge string plus "+Inf" (the exposition format)."""
        with self._lock:
            cumulative: Dict[str, int] = {}
            running = 0
            for edge, n in zip(self.edges, self._bucket_counts):
                running += n
                cumulative[_fmt(edge)] = running
            cumulative["+Inf"] = self._count
            return {
                "sum": self._sum,
                "count": self._count,
                "mean": (self._sum / self._count) if self._count else 0.0,
                "buckets": cumulative,
            }


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Thread-safe get-or-create store of metrics keyed (name, labels)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, LabelItems], Any] = {}
        self._kinds: Dict[str, str] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, Any], **kw: Any) -> Any:
        key = (name, _label_items(labels))
        with self._lock:
            existing_kind = self._kinds.get(name)
            if existing_kind is not None and existing_kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as a "
                    f"{existing_kind}, cannot reuse as a {kind}"
                )
            metric = self._metrics.get(key)
            if metric is None:
                metric = _KINDS[kind](**kw)
                self._metrics[key] = metric
                self._kinds[name] = kind
            return metric

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(
        self,
        name: str,
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
        **labels: Any,
    ) -> Histogram:
        return self._get("histogram", name, labels, buckets=buckets)

    def reset(self) -> None:
        """Drops every metric (tests / per-window benchmark phases)."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()

    # -- reads -------------------------------------------------------------

    def metric_names(self) -> Set[str]:
        """Names currently registered (used to avoid duplicate TYPE lines
        when a merged child snapshot shares a name with this registry)."""
        with self._lock:
            return {name for name, _ in self._metrics}

    def _items(self) -> List[Tuple[str, LabelItems, str, Any]]:
        with self._lock:
            return [
                (name, items, self._kinds[name], metric)
                for (name, items), metric in sorted(self._metrics.items())
            ]

    def counter_total(self, name: str, **label_filter: Any) -> float:
        """Sum of ``name`` across every label set matching the (possibly
        partial) filter — e.g. commits for one replica_id over all ranks."""
        want = dict(_label_items(label_filter))
        total = 0.0
        for metric_name, items, kind, metric in self._items():
            if metric_name != name or kind != "counter":
                continue
            have = dict(items)
            if all(have.get(k) == v for k, v in want.items()):
                total += metric.value
        return total

    def gauge_value(self, name: str, **labels: Any) -> Optional[float]:
        key = (name, _label_items(labels))
        with self._lock:
            metric = self._metrics.get(key)
        return metric.value if isinstance(metric, Gauge) else None

    def histogram_stats(self, name: str, **label_filter: Any) -> Dict[str, Any]:
        """Aggregated {"sum","count","mean"} over matching label sets."""
        want = dict(_label_items(label_filter))
        total_sum, total_count = 0.0, 0
        for metric_name, items, kind, metric in self._items():
            if metric_name != name or kind != "histogram":
                continue
            have = dict(items)
            if all(have.get(k) == v for k, v in want.items()):
                stats = metric.stats()
                total_sum += stats["sum"]
                total_count += stats["count"]
        return {
            "sum": total_sum,
            "count": total_count,
            "mean": (total_sum / total_count) if total_count else 0.0,
        }

    # -- exporters ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe dump: {"counters"|"gauges"|"histograms": {name:
        [{"labels": {...}, ...value fields}]}}."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, items, kind, metric in self._items():
            entry: Dict[str, Any] = {"labels": dict(items)}
            if kind == "histogram":
                entry.update(metric.stats())
            else:
                entry["value"] = metric.value
            out[kind + "s"].setdefault(name, []).append(entry)
        return out

    def prometheus_text(self) -> str:
        lines: List[str] = []
        seen_type: set = set()
        for name, items, kind, metric in self._items():
            if name not in seen_type:
                seen_type.add(name)
                lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                stats = metric.stats()
                for le, count in stats["buckets"].items():
                    bucket_items = items + (("le", le),)
                    lines.append(
                        f"{name}_bucket{_label_str(bucket_items)} {count}"
                    )
                lines.append(f"{name}_sum{_label_str(items)} {_fmt(stats['sum'])}")
                lines.append(f"{name}_count{_label_str(items)} {stats['count']}")
            else:
                lines.append(f"{name}{_label_str(items)} {_fmt(metric.value)}")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()


class WindowedSeries:
    """Fixed-size, byte-budgeted ring of per-window aggregate dicts.

    The registry above is cumulative-only; this is the windowed time-series
    layer on top (history.py's budgeting discipline applied to metrics):
    each appended window is a JSON-safe dict, its retained cost is its
    compact-JSON encoding size, and the ring evicts oldest-first past
    EITHER bound (``max_windows`` windows or ``max_bytes`` bytes, always
    keeping the newest window) — so rates and percentiles over recent
    windows stay queryable live without unbounded growth. First consumer:
    the goodput ledger (torchft_tpu/goodput.py); the class is generic so
    future planes can ring their own windows.
    """

    def __init__(self, max_windows: int = 60, max_bytes: int = 262144) -> None:
        self.max_windows = max(1, int(max_windows))
        self.max_bytes = max(1, int(max_bytes))
        self._ring: List[Tuple[Dict[str, Any], int]] = []
        self._bytes = 0
        self._lock = threading.Lock()
        self._evicted = 0

    def append(self, window: Dict[str, Any]) -> None:
        size = len(json.dumps(window, separators=(",", ":"), default=str))
        with self._lock:
            self._ring.append((window, size))
            self._bytes += size
            while len(self._ring) > 1 and (
                len(self._ring) > self.max_windows or self._bytes > self.max_bytes
            ):
                _, evicted_size = self._ring.pop(0)
                self._bytes -= evicted_size
                self._evicted += 1

    def windows(self) -> List[Dict[str, Any]]:
        """Retained windows, oldest first."""
        with self._lock:
            return [window for window, _ in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def evicted(self) -> int:
        """Windows dropped by either budget so far."""
        with self._lock:
            return self._evicted

    def values(self, key: str) -> List[float]:
        """Numeric ``window[key]`` values across retained windows (windows
        without the key, or with a non-numeric value, are skipped)."""
        out: List[float] = []
        for window in self.windows():
            value = window.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                out.append(float(value))
        return out

    def rate(self, key: str) -> Optional[float]:
        """Mean of ``window[key]`` over retained windows (None when empty)."""
        values = self.values(key)
        return sum(values) / len(values) if values else None

    def percentile(self, key: str, q: float) -> Optional[float]:
        """Nearest-rank percentile of ``window[key]`` (``q`` in [0, 100])."""
        values = sorted(self.values(key))
        if not values:
            return None
        rank = min(len(values) - 1, max(0, int(round(q / 100.0 * (len(values) - 1)))))
        return values[rank]


# -- module-level conveniences bound to the default registry ----------------


def counter(name: str, **labels: Any) -> Counter:
    return REGISTRY.counter(name, **labels)


def gauge(name: str, **labels: Any) -> Gauge:
    return REGISTRY.gauge(name, **labels)


def histogram(
    name: str, buckets: Iterable[float] = DEFAULT_TIME_BUCKETS, **labels: Any
) -> Histogram:
    return REGISTRY.histogram(name, buckets=buckets, **labels)


def inc(name: str, amount: float = 1.0, **labels: Any) -> None:
    REGISTRY.counter(name, **labels).inc(amount)


def set_gauge(name: str, value: float, **labels: Any) -> None:
    REGISTRY.gauge(name, **labels).set(value)


def observe(name: str, value: float, **labels: Any) -> None:
    REGISTRY.histogram(name, **labels).observe(value)


@contextmanager
def timer(name: str, **labels: Any) -> Generator[None, None, None]:
    """Times the with-body into histogram ``name`` (seconds)."""
    start = time.perf_counter()
    try:
        yield
    finally:
        observe(name, time.perf_counter() - start, **labels)


def snapshot() -> Dict[str, Any]:
    return REGISTRY.snapshot()


def snapshot_to_prometheus(
    snap: Dict[str, Any],
    extra_labels: Optional[Dict[str, str]] = None,
    skip_type_names: Iterable[str] = (),
) -> str:
    """Renders a :func:`snapshot`-shaped dict (possibly from ANOTHER
    process, e.g. the heal-serving child's scraped registry) into the
    Prometheus exposition format, adding ``extra_labels`` to every series
    so merged foreign series stay distinguishable. Names in
    ``skip_type_names`` suppress the ``# TYPE`` line (already emitted by
    the local registry). Best-effort on malformed input: bad entries are
    skipped, never raised."""
    extra = tuple(sorted((extra_labels or {}).items()))
    skip = set(skip_type_names)
    kind_of = {"counters": "counter", "gauges": "gauge", "histograms": "histogram"}
    lines: List[str] = []
    seen_type: set = set()
    for section, kind in kind_of.items():
        for name, entries in sorted((snap.get(section) or {}).items()):
            for entry in entries:
                try:
                    items = _label_items({**entry.get("labels", {}), **dict(extra)})
                    if name not in seen_type and name not in skip:
                        seen_type.add(name)
                        lines.append(f"# TYPE {name} {kind}")
                    if kind == "histogram":
                        for le, count in entry.get("buckets", {}).items():
                            bucket_items = items + (("le", str(le)),)
                            lines.append(
                                f"{name}_bucket{_label_str(bucket_items)} {count}"
                            )
                        lines.append(
                            f"{name}_sum{_label_str(items)} {_fmt(entry['sum'])}"
                        )
                        lines.append(
                            f"{name}_count{_label_str(items)} {entry['count']}"
                        )
                    else:
                        lines.append(
                            f"{name}{_label_str(items)} {_fmt(entry['value'])}"
                        )
                except (KeyError, TypeError, ValueError):
                    continue
    return "\n".join(lines) + ("\n" if lines else "")


def prometheus_text() -> str:
    return REGISTRY.prometheus_text()


def counter_total(name: str, **label_filter: Any) -> float:
    return REGISTRY.counter_total(name, **label_filter)


def gauge_value(name: str, **labels: Any) -> Optional[float]:
    return REGISTRY.gauge_value(name, **labels)


def histogram_stats(name: str, **label_filter: Any) -> Dict[str, Any]:
    return REGISTRY.histogram_stats(name, **label_filter)


# -- HTTP exposition --------------------------------------------------------


def _serve_metrics_http(
    handler: Any,
    registry: Registry,
    path: str,
    extra_text: Optional[Any] = None,
    extra_json: Optional[Any] = None,
) -> bool:
    """Shared route logic for any BaseHTTPRequestHandler: serves
    ``/metrics`` (Prometheus text) and ``/metrics.json`` (snapshot);
    returns False when the path is not a metrics route. Reused by the
    checkpoint transport's server so every replica already listening for
    heals answers scrapes on the same port. ``extra_text``/``extra_json``
    (callables) let a caller merge foreign series — e.g. the donor merges
    its heal-serving child's scraped registry; both are best-effort and
    never fail the scrape."""
    route = path.split("?", 1)[0].rstrip("/")
    if route == "/trace.json":
        # The fleet trace plane's pull surface: the process journal's full
        # ring + clock info, merged across replicas by scripts/
        # fleet_trace.py. Lazy import keeps metrics a leaf module.
        try:
            from torchft_tpu import tracing

            payload = tracing.trace_json_payload()
        except Exception as e:  # noqa: BLE001 — scrape must never fail
            payload = {"error": str(e)}
        body = json.dumps(payload).encode()
        content_type = "application/json"
    elif route == "/metrics":
        body_text = registry.prometheus_text()
        if extra_text is not None:
            try:
                body_text += extra_text() or ""
            except Exception:  # noqa: BLE001 — merge is best-effort
                pass
        body = body_text.encode()
        content_type = "text/plain; version=0.0.4; charset=utf-8"
    elif route == "/metrics.json":
        payload = {"ts": time.time(), "metrics": registry.snapshot()}
        if extra_json is not None:
            try:
                extra = extra_json()
                if extra:
                    payload.update(extra)
            except Exception:  # noqa: BLE001 — merge is best-effort
                pass
        body = json.dumps(payload).encode()
        content_type = "application/json"
    else:
        return False
    handler.send_response(200)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)
    return True


class MetricsHTTPServer:
    """Standalone threaded /metrics endpoint (processes with no checkpoint
    transport: lighthouse daemons, benchmarks, the doctor's probe target)."""

    def __init__(self, port: int, registry: Registry = REGISTRY) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:  # silence
                pass

            def do_GET(self) -> None:
                if not _serve_metrics_http(self, registry, self.path):
                    self.send_error(404, "unknown route (try /metrics)")

        self._server = ThreadingHTTPServer(("", port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=functools.partial(self._server.serve_forever, poll_interval=0.05), daemon=True, name="tpuft-metrics"
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


_HTTP_SERVER: Optional[MetricsHTTPServer] = None
_HTTP_LOCK = threading.Lock()


def start_http_server(port: int, registry: Registry = REGISTRY) -> MetricsHTTPServer:
    return MetricsHTTPServer(port, registry)


def maybe_start_http_server() -> Optional[MetricsHTTPServer]:
    """Starts the per-process /metrics server iff ``$TPUFT_METRICS_PORT``
    is set (idempotent; one server per process). A malformed or
    already-bound port logs and returns None — metrics must never take
    down training."""
    global _HTTP_SERVER
    value = os.environ.get(ENV_PORT)
    if not value:
        return None
    with _HTTP_LOCK:
        if _HTTP_SERVER is not None:
            return _HTTP_SERVER
        try:
            _HTTP_SERVER = start_http_server(int(value))
        except (ValueError, OSError) as e:
            import logging

            logging.getLogger(__name__).warning(
                "TPUFT_METRICS_PORT=%r: /metrics server not started (%s)",
                value, e,
            )
            return None
        return _HTTP_SERVER


def push_interval_sec(default: float = 10.0) -> float:
    """The store-push rate limit from ``$TPUFT_METRICS_PUSH_SEC``
    (malformed values fall back to the default; <= 0 disables)."""
    try:
        return float(os.environ.get(ENV_PUSH_SEC, str(default)))
    except ValueError:
        return default
