"""Model zoo: demo models plus the transformer family used for benchmarks."""
