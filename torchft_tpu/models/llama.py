"""Llama-family transformer, TPU-first.

The flagship model family for the fault-tolerant training stack — the
reference composes with torchtitan's Llama 3 configs for its production
story (BASELINE.md: FT-DDP Llama-3 8B, FT-HSDP 70B, DiLoCo 8B), so this
module provides the same family natively: RMSNorm, rotary embeddings, GQA
attention, SwiGLU MLP, tied-or-untied output head.

TPU-first choices:
- bfloat16 activations/weights by default, float32 RMSNorm accumulation and
  logits — keeps matmuls on the MXU at full tile rate;
- static shapes everywhere; the causal mask is computed inline (no python
  control flow under jit);
- attention dispatches to ring attention (ops/ring_attention.py) when a
  sequence-parallel axis is present in the ambient mesh, enabling context
  lengths sharded across devices;
- :func:`sharding_plan` gives PartitionSpecs for fsdp/tp axes (megatron
  layout: column-parallel qkv/up, row-parallel out/down) consumed by
  ``jax.jit`` via NamedSharding;
- ``remat`` ("full"/"dots") and ``scan_layers`` on the config: gradient
  checkpointing and a lax.scan'd layer stack, so 70B-class/long-context
  steps fit in HBM and compile in O(1) HLO size in depth.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from torchft_tpu.utils.platform import on_tpu

__all__ = [
    "LlamaConfig",
    "Llama",
    "CONFIGS",
    "large_bench_config",
    "sharding_plan",
    "plan_shardings",
    "apply_sharding_plan",
    "cross_entropy_loss",
]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    # "auto": ring attention iff an 'sp' axis is in the ambient mesh, else
    # for long sequences the fused Pallas flash kernel on real TPU /
    # blockwise elsewhere, else dense. Explicit options:
    # "dense", "blockwise" (O(s*block) memory, ops/ring_attention.py),
    # "flash" (fused Pallas TPU kernel forward + same flash backward,
    # ops/flash_attention.py; interpret-mode off-TPU), "ring".
    attention_impl: str = "auto"
    sp_axis: str = "sp"
    attention_block_size: int = 512
    # KV-block length for the flash path only (the kernel's sequential
    # accumulation axis). The on-chip sweep (scripts/flash_block_sweep.py,
    # TPU v5 lite) puts the knee at 512x1024: vs 512x512 the s=8192
    # fwd+bwd drops 47.2 -> 37.9 ms. None = attention_block_size.
    attention_block_k: Optional[int] = 1024
    # Mosaic kernels cannot be auto-partitioned by XLA SPMD: under a
    # jit-with-mesh (fsdp/tp/dp sharded train step) the flash path must
    # shard_map ITSELF or lowering fails outright. These name the mesh
    # axes it maps over when the ambient mesh binds them (batch over the
    # data axes, q/kv heads over the tensor axis — the megatron layout
    # sharding_plan uses); axes that are absent, size-1, already manual,
    # or non-dividing are dropped per-call.
    flash_batch_axes: Tuple[str, ...] = ("dp", "fsdp")
    flash_tp_axis: Optional[str] = "tp"
    # Route the ring path's per-hop block compute through the fused Pallas
    # kernel (ops/flash_attention.py) instead of the jnp scan update.
    ring_use_flash: bool = False
    # auto picks blockwise over dense at/after this sequence length.
    blockwise_min_seq: int = 2048
    # Rematerialization (gradient checkpointing): trade FLOPs for HBM so
    # long-context / 70B-class steps fit. "full" recomputes each block in
    # the backward; "dots" keeps MXU dot outputs and recomputes the cheap
    # elementwise/VPU work (jax.checkpoint_policies.checkpoint_dots) —
    # usually the right TPU default when activations don't fit.
    remat: str = "none"
    # Vocab slab width for the fused linear+CE loss path (``targets=`` in
    # __call__): the (b, s, vocab) logits — 8 GiB at 8x2048x128k f32 —
    # are never materialized (ops/cross_entropy.py). None = dense CE.
    loss_vocab_chunk: Optional[int] = None
    # lax.scan over the layer stack: one traced/compiled Block for the
    # whole depth instead of n_layers inlined copies — O(1) HLO size and
    # compile time in depth (matters at 80 layers). Params gain a leading
    # layer axis; sharding_plan/apply_sharding_plan handle both layouts.
    scan_layers: bool = False

    def __post_init__(self) -> None:
        valid = ("auto", "dense", "blockwise", "flash", "ring")
        if self.attention_impl not in valid:
            raise ValueError(
                f"attention_impl={self.attention_impl!r} is not one of {valid}"
            )
        if self.remat not in ("none", "full", "dots"):
            raise ValueError(
                f"remat={self.remat!r} is not one of ('none', 'full', 'dots')"
            )

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS: Dict[str, LlamaConfig] = {
    # Test/bench-sized models.
    "tiny": LlamaConfig(
        vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=128, max_seq_len=256, dtype=jnp.float32,
    ),
    "small": LlamaConfig(
        vocab_size=8192, dim=512, n_layers=6, n_heads=8, n_kv_heads=4,
        ffn_hidden=1536, max_seq_len=2048,
    ),
    # Llama-3 family shapes (parity with the reference's torchtitan configs).
    "1b": LlamaConfig(
        vocab_size=128256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        ffn_hidden=8192, max_seq_len=8192,
    ),
    "8b": LlamaConfig(
        vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        ffn_hidden=14336, max_seq_len=8192,
    ),
    "70b": LlamaConfig(
        vocab_size=128256, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        ffn_hidden=28672, max_seq_len=8192,
    ),
}


def large_bench_config(**overrides) -> LlamaConfig:
    """The ~445M-parameter flagship benchmark config — the ONE definition.

    Shared by bench.py's ``TPUFT_BENCH_MODEL=large`` run, the chipless
    HBM sizing probe (scripts/hbm_probe.py), the compile-cost bench
    (benchmarks/compile_bench.py base dims), and the Mosaic
    cross-lowering gate (tests/test_mosaic_lowering.py) so the gate and
    probes always track the config the bench actually runs — the config
    used to be copied verbatim into all four files, and a retune in one
    silently drifted the other three.

    The choices are on-chip measurements (TPU v5 lite, 2026-07-31):

    - head geometry 8x128, not 16x64: identical params and FLOPs at
      dim 1024, but the 64-wide heads starve the 128-lane MXU —
      measured 306 -> 214 ms/step (1.43x) at batch 4 x seq 2048, i.e.
      43.5% -> 63.1% MFU under the bench's 6N + 12*L*d*s accounting
      (BENCH_TPU_LARGE.json).
    - remat="dots" + batch 4: the 15.75 GB HBM budget, sized by
      chipless AOT compiles (scripts/hbm_probe.py) — batch 8 without
      remat needs ~29 GB.
    - flash attention + scanned layers + fused CE: the long-sequence
      kernel path, O(1) HLO in depth, and no materialized logits.

    ``overrides`` are dataclasses.replace fields (the compile bench
    flips scan_layers/remat to measure their cost; the HBM probe sweeps
    remat and sequence length).
    """
    base = LlamaConfig(
        vocab_size=32768, dim=1024, n_layers=24, n_heads=8, n_kv_heads=4,
        ffn_hidden=4096, max_seq_len=2048, dtype=jnp.bfloat16,
        attention_impl="flash", scan_layers=True, loss_vocab_chunk=4096,
        remat="dots",
    )
    return replace(base, **overrides) if overrides else base


def _rope_freqs(head_dim: int, theta: float) -> jnp.ndarray:
    return 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """x: (batch, seq, heads, head_dim); positions: (batch, seq)."""
    freqs = _rope_freqs(x.shape[-1], theta)  # (head_dim/2,)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (b, s, hd/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


def _sp_axis_in_mesh(axis: str) -> bool:
    """True when the ambient abstract mesh binds ``axis`` with size > 1.

    Reads only the public ``jax.sharding.get_abstract_mesh`` accessor, which
    sees every context the ring path can actually execute in: shard_map
    tracing (Manual axes — the only place ``lax.ppermute(axis_name=...)``
    is bound) and ``jax.set_mesh``/``use_mesh`` scopes. A legacy
    ``with mesh:`` block alone is invisible here, but it also cannot bind
    the collective axis name ring attention requires — under it ``auto``
    correctly computes local attention, and an explicit
    ``attention_impl='ring'`` fails loudly at trace time with an
    unbound-axis-name error (test_models.py asserts that loud path) rather
    than silently returning per-shard results."""
    abstract = jax.sharding.get_abstract_mesh()
    if abstract is None or axis not in getattr(abstract, "axis_names", ()):
        return False
    return abstract.shape[axis] > 1


def _largest_dividing_subset(
    axes: Tuple[str, ...], sizes: Dict[str, int], n: int
) -> Tuple[str, ...]:
    """The subset of ``axes`` with the largest shard-count product that
    divides ``n``, in the original axis order (the spec/flatten order).
    Ties prefer more axes (finer sharding layout), then earlier subsets.
    Brute force: flash_batch_axes is 2-3 names, never a search problem."""
    best: Tuple[str, ...] = ()
    best_size = 1
    for mask in range(1, 1 << len(axes)):
        subset = tuple(a for i, a in enumerate(axes) if mask & (1 << i))
        size = 1
        for a in subset:
            size *= sizes[a]
        if n % size == 0 and (
            size > best_size or (size == best_size and len(subset) > len(best))
        ):
            best, best_size = subset, size
    return best


# (shape, dropped-axes) combinations already warned about — the fallback
# fires on every traced call, and a sharded train step retraces per shape.
_FLASH_REPLICATION_WARNED: set = set()


def _warn_flash_replicated(
    dropped: Tuple[str, ...], kept: Tuple[str, ...], tp, dims, mesh
) -> None:
    """Once-per-shape warning when a usable mesh axis falls back to
    replication because the batch/head count doesn't divide it: the kernel
    still runs (inside the manual context), but the compute is replicated
    — and q/k/v all-gathered — across every dropped axis, a large silent
    performance cliff worth surfacing."""
    b, h, kv_heads = dims
    key = (dims, dropped, kept, tp)
    if key in _FLASH_REPLICATION_WARNED:
        return
    _FLASH_REPLICATION_WARNED.add(key)
    sizes = ", ".join(f"{a}={mesh.shape[a]}" for a in dropped)
    logging.getLogger(__name__).warning(
        "flash attention: batch=%d heads=%d/%d does not divide mesh axis(es) "
        "%s — the kernel replicates its compute (and all-gathers q/k/v) "
        "across them; kept batch axes %s, tp axis %s. Resize the batch/head "
        "counts or flash_batch_axes to restore full sharding.",
        b, h, kv_heads, sizes, kept or "()", tp,
    )


def _flash_under_ambient_mesh(cfg: LlamaConfig, q, k, v, scale: float):
    """Dispatches the fused Pallas kernel, shard_mapping it over the
    ambient mesh's data/tensor axes when one is bound.

    XLA SPMD cannot partition a Mosaic custom call ("Mosaic kernels
    cannot be automatically partitioned") — so inside a sharded train
    step (jit with a NamedSharding mesh: the FTMesh/HSDP path) a bare
    ``flash_attention`` fails to lower. Attention is embarrassingly
    parallel over (batch, head) in the non-SP case, so the wrapper maps
    batch over ``cfg.flash_batch_axes`` and heads over
    ``cfg.flash_tp_axis`` — the same layout ``sharding_plan`` gives the
    QKV projections, so no resharding is introduced — and leaves any
    other mesh axes automatic (``axis_names``: partial-manual). Axes
    that are absent, size-1, or already manual (the model is inside a
    caller's shard_map — shapes are already local and the kernel just
    works) are excluded from the map; with none left the plain call is
    used. A usable axis whose batch/head count doesn't divide STAYS
    manual but drops out of the specs — the kernel then computes
    replicated over it, because a bare pallas_call under jit-with-mesh
    is the exact lowering error this wrapper exists to avoid, dividing
    or not. GQA inside each shard is preserved: h and kv_heads are
    divided by the same tp factor, so the group ratio is unchanged.

    The ambient mesh is read via ``jax.sharding.get_abstract_mesh`` —
    bind it with ``jax.set_mesh(mesh)`` (what the in-repo drills and
    examples do); a legacy ``with mesh:`` block alone is invisible
    here, leaving the bare kernel to fail lowering on a real pod with
    XLA's own "wrap the call in a shard_map" error."""
    from torchft_tpu.ops.flash_attention import flash_attention

    from jax.sharding import AxisType

    call = partial(
        flash_attention,
        scale=scale,
        block_q=cfg.attention_block_size,
        block_k=cfg.attention_block_k or cfg.attention_block_size,
    )
    mesh = jax.sharding.get_abstract_mesh()
    axis_types = dict(
        zip(getattr(mesh, "axis_names", ()), getattr(mesh, "axis_types", ()))
    )

    def usable(axis: Optional[str]) -> bool:
        if axis is None or axis not in axis_types:
            return False
        if mesh.shape[axis] <= 1:
            return False
        # Already-manual axes (the model is inside a caller's shard_map)
        # must not be wrapped again — shapes are already local there and
        # a nested map over local shapes mis-divides them.
        return axis_types[axis] != AxisType.Manual

    b, _, h, _ = q.shape
    kv_heads = k.shape[2]
    # Every usable axis becomes manual: even when a dim doesn't divide
    # (so its spec entry drops to None and the compute replicates over
    # that axis), the kernel must still run inside the manual context —
    # a bare pallas_call under jit-with-mesh is the exact lowering error
    # this wrapper exists to avoid, dividing or not.
    manual = {a for a in cfg.flash_batch_axes if usable(a)}
    if usable(cfg.flash_tp_axis):
        manual.add(cfg.flash_tp_axis)
    if not manual:
        return call(q, k, v)
    usable_batch = tuple(a for a in cfg.flash_batch_axes if a in manual)
    # Non-dividing fallback is PER-AXIS, not all-or-nothing: keep the
    # largest dividing subset (by total shard count) of the usable batch
    # axes instead of replicating over every one of them the moment the
    # product stops dividing — e.g. batch 4 on dp=2 x fsdp=4 still shards
    # over dp. Any axis left out replicates the attention compute (and
    # all-gathers q/k/v) across it — a silent performance cliff, so it
    # warns once per shape below.
    batch_axes = _largest_dividing_subset(
        usable_batch, {a: mesh.shape[a] for a in usable_batch}, b
    )
    tp = cfg.flash_tp_axis if cfg.flash_tp_axis in manual else None
    if tp is not None and (h % mesh.shape[tp] or kv_heads % mesh.shape[tp]):
        tp = None
    dropped = tuple(a for a in usable_batch if a not in batch_axes)
    if cfg.flash_tp_axis in manual and tp is None:
        dropped += (cfg.flash_tp_axis,)
    if dropped:
        _warn_flash_replicated(dropped, batch_axes, tp, (b, h, kv_heads), mesh)
    bspec = batch_axes if batch_axes else None
    spec = P(bspec, None, tp, None)
    return jax.shard_map(
        call,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual,
    )(q, k, v)


def causal_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float
) -> jnp.ndarray:
    """Grouped-query causal attention; fp32 softmax on the VPU, matmuls in
    the input dtype on the MXU. Shapes: q (b,s,h,d); k,v (b,s,kv,d)."""
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    q = q.reshape(b, s, kv_heads, group, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", q, k).astype(jnp.float32) * scale
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(mask[None, None, None, :, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dense = partial(
            nn.DenseGeneral, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.dtype
        )
        q = dense(features=(cfg.n_heads, cfg.head_dim), name="wq")(x)
        k = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wk")(x)
        v = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wv")(x)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

        scale = cfg.head_dim**-0.5
        use_ring = cfg.attention_impl == "ring" or (
            cfg.attention_impl == "auto" and _sp_axis_in_mesh(cfg.sp_axis)
        )
        if use_ring:
            from torchft_tpu.ops.ring_attention import (
                ring_attention,
                ring_attention_flash,
            )

            ring = ring_attention_flash if cfg.ring_use_flash else ring_attention
            out = ring(q, k, v, axis_name=cfg.sp_axis, scale=scale)
        elif cfg.attention_impl == "flash" or (
            cfg.attention_impl == "auto"
            and x.shape[1] >= cfg.blockwise_min_seq
            and on_tpu()
        ):
            # On real TPU hardware, auto prefers the fused Pallas kernel for
            # long sequences: same O(s·block) memory as blockwise but one
            # Mosaic kernel instead of a jnp scan (re-verified against dense
            # on every live-chip bench via verify_on_chip). Under a sharded
            # train step the dispatcher shard_maps the kernel itself —
            # Mosaic custom calls cannot be auto-partitioned by XLA SPMD.
            out = _flash_under_ambient_mesh(cfg, q, k, v, scale)
        elif cfg.attention_impl == "blockwise" or (
            cfg.attention_impl == "auto" and x.shape[1] >= cfg.blockwise_min_seq
        ):
            from torchft_tpu.ops.ring_attention import blockwise_attention

            out = blockwise_attention(
                q, k, v, scale=scale, block_size=cfg.attention_block_size
            )
        else:
            out = causal_attention(q, k, v, scale)
        return dense(features=cfg.dim, axis=(-2, -1), name="wo")(out)


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.dtype)
        gate = dense(cfg.ffn_hidden, name="w_gate")(x)
        up = dense(cfg.ffn_hidden, name="w_up")(x)
        return dense(cfg.dim, name="w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        x = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.norm_eps, cfg.dtype, name="attn_norm")(x), positions
        )
        x = x + MLP(cfg, name="mlp")(RMSNorm(cfg.norm_eps, cfg.dtype, name="mlp_norm")(x))
        return x


def _remat_policy(remat: str):
    return jax.checkpoint_policies.checkpoint_dots if remat == "dots" else None


class _ScanCell(nn.Module):
    """One Block in ``(carry, broadcast) -> (carry, out)`` shape for
    ``nn.scan``; params live under ``<stack>/block`` with a leading layer
    axis added by the scan's ``variable_axes={'params': 0}``."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, positions: jnp.ndarray):
        return Block(self.config, name="block")(x, positions), None


class _LMHead(nn.Module):
    """The output projection, param-compatible with ``nn.Dense`` (same
    ``lm_head/kernel`` path, lecun-normal init, dtype promotion): owning
    the kernel directly lets the fused loss path hand it to
    :func:`~torchft_tpu.ops.cross_entropy.chunked_cross_entropy` without
    ever forming the logits."""

    config: LlamaConfig

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        targets: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        cfg = self.config
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (cfg.dim, cfg.vocab_size),
            cfg.dtype,
        )
        if targets is None:
            return jnp.dot(x, kernel.astype(cfg.dtype))
        from torchft_tpu.ops.cross_entropy import chunked_cross_entropy

        return chunked_cross_entropy(x, kernel, targets, cfg.loss_vocab_chunk)


class Llama(nn.Module):
    """Callable two ways: ``apply(params, tokens)`` returns logits;
    ``apply(params, tokens, targets=targets)`` returns the mean token
    cross-entropy directly — with ``config.loss_vocab_chunk`` set, via the
    fused linear+CE that never materializes the logits."""

    config: LlamaConfig

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        positions: Optional[jnp.ndarray] = None,
        targets: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        cfg = self.config
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape
            )
        embed = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.dtype,
            name="tok_embed",
        )
        x = embed(tokens)
        if cfg.scan_layers:
            cell = _ScanCell
            if cfg.remat != "none":
                # prevent_cse is safe (and standard) under scan: the loop
                # boundary already blocks the CSE remat would otherwise fight.
                cell = nn.remat(
                    cell, policy=_remat_policy(cfg.remat), prevent_cse=False
                )
            stack = nn.scan(
                cell,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                in_axes=nn.broadcast,
            )
            x, _ = stack(cfg, name="layers")(x, positions)
        else:
            block = Block
            if cfg.remat != "none":
                block = nn.remat(Block, policy=_remat_policy(cfg.remat))
            for layer in range(cfg.n_layers):
                x = block(cfg, name=f"layer_{layer}")(x, positions)
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        if targets is not None:
            from torchft_tpu.ops.cross_entropy import chunked_cross_entropy

            if cfg.tie_embeddings:
                return chunked_cross_entropy(
                    x, embed.embedding.T, targets, cfg.loss_vocab_chunk
                )
            return _LMHead(cfg, name="lm_head")(x, targets)
        if cfg.tie_embeddings:
            logits = embed.attend(x)
        else:
            logits = _LMHead(cfg, name="lm_head")(x)
        return logits.astype(jnp.float32)


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    token_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(token_logp)


def sharding_plan(
    fsdp_axis: Optional[str] = "fsdp", tp_axis: Optional[str] = "tp"
) -> Dict[str, Any]:
    """Regex -> PartitionSpec map for Llama params (megatron layout:
    column-parallel wq/wk/wv/w_gate/w_up, row-parallel wo/w_down; embeddings
    vocab-sharded on tp; everything else fsdp-sharded on dim 0)."""
    f, t = fsdp_axis, tp_axis
    return {
        r".*tok_embed/embedding": P(t, f),
        r".*lm_head/kernel": P(f, t),
        r".*(wq|wk|wv)/kernel": P(f, t, None),
        r".*wo/kernel": P(t, None, f),
        r".*(w_gate|w_up)/kernel": P(f, t),
        r".*w_down/kernel": P(t, f),
        r".*scale": P(),
    }


def plan_shardings(params: Any, mesh: Any, plan: Dict[str, Any]) -> Any:
    """Maps each param leaf (by its flattened path) to a NamedSharding from
    the plan; unmatched leaves replicate. Works on abstract leaves
    (ShapeDtypeStruct / eval_shape output) and abstract meshes too — only
    ``.ndim``/``.shape`` are read — so AOT lowering of a sharded train
    step (tests/test_mosaic_lowering.py's scale gate) can build the exact
    in_shardings the runtime path uses without materializing anything."""
    import re

    from jax.sharding import NamedSharding

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)

    def path_str(path: Tuple) -> str:
        return "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )

    out = []
    for path, leaf in flat:
        name = path_str(path)
        spec = P()
        for pattern, candidate in plan.items():
            if re.fullmatch(pattern, name):
                spec = candidate
                break
        # Scanned stacks carry a leading layer axis (scan_layers=True):
        # the plan describes the per-layer shape, so shift it right and
        # replicate over the stack axis.
        if len(spec) and leaf.ndim == len(spec) + 1:
            spec = P(None, *spec)
        # Drop spec axes that don't divide the leaf's dims.
        fixed = []
        for dim, entry in enumerate(spec):
            if entry is None:
                fixed.append(None)
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            size = 1
            for axis in axes:
                size *= mesh.shape.get(axis, 1)
            fixed.append(entry if leaf.shape[dim] % size == 0 else None)
        out.append(NamedSharding(mesh, P(*fixed)))
    return jax.tree_util.tree_unflatten(treedef, out)


def apply_sharding_plan(params: Any, mesh: Any, plan: Dict[str, Any]) -> Any:
    """Places each param leaf onto its :func:`plan_shardings` sharding
    (one batched transfer — per-leaf puts would serialize hundreds of
    copies over a slow host↔device link)."""
    return jax.device_put(params, plan_shardings(params, mesh, plan))
