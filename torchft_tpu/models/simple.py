"""Demo models for the fault-tolerance examples.

Parity targets: the reference's CIFAR-10 CNN (+ an optional dummy embedding
that inflates the gradient payload to lengthen the communication window for
fault injection, train_ddp.py:126-131) and the 2-layer MLP used by the DiLoCo
demo (train_diloco.py:118-119).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["DemoCNN", "DemoMLP"]


class DemoCNN(nn.Module):
    """Small conv net for 32x32 images (CIFAR-shaped inputs).

    ``padding_mb``: adds an unused embedding table of roughly that many
    megabytes so gradient allreduces move real bytes — fault-injection demos
    want a wide communication window.
    """

    num_classes: int = 10
    padding_mb: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.padding_mb > 0:
            rows = (self.padding_mb * 1024 * 1024) // (4 * 128)
            padding = self.param(
                "comm_padding", nn.initializers.zeros, (rows, 128), jnp.float32
            )
            # Touch the padding so it receives (zero) gradients and rides the
            # allreduce, like the reference's dummy embedding.
            x = x + jnp.sum(padding) * 0.0
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class DemoMLP(nn.Module):
    """2-layer MLP (DiLoCo demo model)."""

    hidden: int = 128
    out: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.out)(x)
