"""Device kernels: quantization, attention, and other hot ops."""
