"""Memory-efficient fused linear + cross-entropy.

The LM head is the single largest activation in causal-LM training: at
batch 8 x seq 2048 x vocab 128k the f32 logits alone are 8 GiB, and the
softmax/backward temporaries double it — often more HBM than the whole
rest of the step. The reference stack inherits torch's materialized
``F.cross_entropy`` over full logits; this op is the TPU-first
alternative: ``lax.scan`` over vocab chunks with an online logsumexp
(the flash-attention trick applied to the vocab axis), so only one
``(..., chunk)`` logits slab is ever live.

A ``custom_vjp`` keeps the backward at the same footprint: the forward
saves ``(x, w, targets, lse)`` — inputs plus one f32 scalar per row; the
backward re-computes each chunk's logits from ``(x, w)``, forms
``softmax - onehot`` in the chunk, and accumulates ``dx`` and the
``dw`` slab in final layout — full logits are never materialized in
either direction (AD through the naive scan would stack per-chunk
residuals and reconstruct exactly the array this op exists to avoid).

FLOPs are identical to the dense path (the matmul is computed once per
direction either way); what changes is peak HBM and the fusion shape.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["chunked_cross_entropy"]


def _chunk_logits(x2, w, start, chunk):
    """(n, d) @ (d, chunk) slice starting at vocab index ``start``."""
    wc = jax.lax.dynamic_slice_in_dim(w, start, chunk, axis=1)
    return jnp.dot(
        x2.astype(jnp.float32), wc.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _chunked_ce(x2, w, targets1, chunk, vocab_valid):
    loss, _ = _ce_fwd(x2, w, targets1, chunk, vocab_valid)
    return loss


def _col_mask(idx, chunk, vocab_valid):
    """(chunk,) validity of this slab's global vocab columns — the tail
    slab of a non-multiple vocab is zero-padded by the wrapper and masked
    out here."""
    return idx * chunk + jnp.arange(chunk) < vocab_valid


def _ce_fwd(x2, w, targets1, chunk, vocab_valid):
    n, d = x2.shape
    vocab = w.shape[1]
    n_chunks = vocab // chunk

    def body(carry, idx):
        m, s, tl = carry  # running max, sum exp, target logit
        logits = _chunk_logits(x2, w, idx * chunk, chunk)  # (n, chunk)
        logits = jnp.where(_col_mask(idx, chunk, vocab_valid), logits, -1e30)
        cmax = jnp.max(logits, axis=1)
        new_m = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(logits - new_m[:, None]), axis=1
        )
        # Gather this chunk's contribution to the target logit.
        local = targets1 - idx * chunk
        in_chunk = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=1
        )[:, 0]
        tl = jnp.where(in_chunk, picked, tl)
        return (new_m, s, tl), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, s, tl), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    loss = jnp.mean(lse - tl)
    return loss, (x2, w, targets1, lse)


def _ce_bwd(chunk, vocab_valid, residuals, g):
    x2, w, targets1, lse = residuals
    n, d = x2.shape
    vocab = w.shape[1]
    n_chunks = vocab // chunk
    scale = g / n  # d(mean)/d(per-row loss)

    def body(carry, idx):
        dx, dw = carry
        logits = _chunk_logits(x2, w, idx * chunk, chunk)
        logits = jnp.where(_col_mask(idx, chunk, vocab_valid), logits, -1e30)
        p = jnp.exp(logits - lse[:, None])  # softmax slab (n, chunk)
        local = targets1 - idx * chunk
        in_chunk = (local >= 0) & (local < chunk)
        onehot = (
            jax.nn.one_hot(jnp.clip(local, 0, chunk - 1), chunk, dtype=jnp.float32)
            * in_chunk[:, None]
        )
        dlogits = (p - onehot) * scale  # (n, chunk) f32
        wc = jax.lax.dynamic_slice_in_dim(w, idx * chunk, chunk, axis=1)
        dx = dx + jnp.dot(
            dlogits, wc.astype(jnp.float32).T, preferred_element_type=jnp.float32
        )
        dwc = jnp.dot(
            x2.astype(jnp.float32).T, dlogits, preferred_element_type=jnp.float32
        )  # (d, chunk)
        # In-place slab write into the final (d, vocab) layout — a stacked
        # (n_chunks, d, chunk) output would force a transient full-size
        # transpose copy on reshape (and see CLAUDE.md on
        # dynamic_update_slice for sliced accumulators under shard_map AD).
        dw = jax.lax.dynamic_update_slice_in_dim(dw, dwc, idx * chunk, axis=1)
        return (dx, dw), None

    (dx, dw), _ = jax.lax.scan(
        body,
        (jnp.zeros((n, d), jnp.float32), jnp.zeros((d, vocab), jnp.float32)),
        jnp.arange(n_chunks),
    )
    return dx.astype(x2.dtype), dw.astype(w.dtype), None


_chunked_ce.defvjp(_ce_fwd, _ce_bwd)


def chunked_cross_entropy(
    x: jnp.ndarray,
    w: jnp.ndarray,
    targets: jnp.ndarray,
    vocab_chunk: Optional[int] = 4096,
) -> jnp.ndarray:
    """Mean token cross-entropy of ``softmax((x @ w))`` against ``targets``
    without materializing the logits.

    Args:
        x: final hidden states ``(..., d)`` (any float dtype; matmuls run
           f32-accumulated).
        w: LM-head kernel ``(d, vocab)`` (for tied embeddings pass
           ``embedding.T``).
        targets: int targets, shape ``x.shape[:-1]``.
        vocab_chunk: vocab slab width. Non-multiple vocabs (Llama-3's
           128256) are handled by zero-padding the tail slab outside the
           custom VJP and masking the padded columns to ``-1e30`` inside
           (AD of the pad restores ``dw``'s true shape). ``None``
           disables chunking (dense one-shot — same math, for small
           vocabs).

    Matches ``cross_entropy_loss(x @ w, targets)`` (models/llama.py) to
    f32 tolerance in value and gradients; peak activation memory drops
    from O(n·vocab) to O(n·vocab_chunk).

    Targets must lie in ``[0, vocab)``; out-of-range values are clamped
    to the nearest valid index (once, here in the wrapper) so the
    chunked and dense paths return the SAME value for invalid input —
    previously the chunked path silently used a 0.0 target logit while
    the dense path clamped (round-3 advisor).
    """
    d = x.shape[-1]
    vocab = w.shape[1]
    x2 = x.reshape(-1, d)
    targets1 = jnp.clip(targets.reshape(-1).astype(jnp.int32), 0, vocab - 1)
    if vocab_chunk is None or vocab_chunk >= vocab:
        logits = jnp.dot(
            x2.astype(jnp.float32), w.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        tl = jnp.take_along_axis(logp, targets1[:, None], axis=1)[:, 0]
        return -jnp.mean(tl)
    pad = (-vocab) % vocab_chunk
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return _chunked_ce(x2, w, targets1, vocab_chunk, vocab)
