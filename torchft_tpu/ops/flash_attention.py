"""Pallas TPU flash attention: the fused-kernel path for the hot op.

The scan-based :func:`torchft_tpu.ops.ring_attention.blockwise_attention`
already gives O(s·block) memory, but each block update is a separate XLA
fusion: scores, mask, softmax bookkeeping, and the PV matmul round-trip
through HBM between blocks. This module fuses the whole online-softmax
inner loop into ONE Pallas kernel so the accumulators (acc, running max,
running sum) live in VMEM for the duration and the two matmuls per block
ride the MXU back-to-back (pallas_guide.md: grid iterated sequentially on
TPU with the last axis minor, which makes cross-grid-step VMEM scratch the
canonical accumulation pattern).

Forward AND backward are fused Pallas kernels on TPU. The backward is the
standard FlashAttention-2 two-pass recompute from the saved (out, lse)
residuals: a dq kernel accumulating over KV blocks and a dkv kernel
accumulating over Q blocks, with the per-row ``delta = rowsum(dO*O)``
identity computed by XLA outside the kernels (it fuses into the
surrounding graph). GQA is handled by emitting per-q-head dk/dv partials
and summing over the group axis outside — keeps every output block
written exactly once per grid pass (no cross-step output aliasing, which
Mosaic cannot express). The scan-based blockwise backward remains the
interpret/CPU fallback (``use_pallas_bwd`` selects; CPU tests run the
Pallas backward in interpret mode explicitly). Run :func:`verify_on_chip`
on a live chip after any kernel change (the CLAUDE.md kernel-verification
gate — every live-chip bench.py run re-executes it, forward and backward);
tests/test_mosaic_lowering.py additionally cross-lowers every kernel here
for a TPU target in the CPU suite, so block-layout violations (the class
interpret mode cannot see) fail fast without the relay.
Note "auto" attention (models/llama.py) SELECTS this kernel on real TPU
for long sequences, so a kernel edit reaches default-configured runs:
never ship one without the on-chip gate.

The reference has no attention code at all (SURVEY.md §2.7: long-sequence
scaling is delegated to torchtitan); this is part of the beyond-reference
long-context stack, sitting below ring attention (which shards the
sequence across chips) as the per-chip kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torchft_tpu.utils.platform import on_tpu

from torchft_tpu.ops.ring_attention import _blockwise_core_bwd

__all__ = [
    "flash_attention",
    "flash_attention_partial",
    "flash_attention_partial_bwd",
    "merge_attention_partials",
]

_NEG_INF = -1e30
_PAD_POS = 2**31 - 1  # position for padded rows: beyond every real query


def _out_struct(shape, dtype, inputs):
    """ShapeDtypeStruct carrying the union of the inputs' varying-mesh-axes
    (vma): under shard_map(check_vma=True) pallas_call outputs must declare
    how they vary over manual axes; outside shard_map the union is empty."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in inputs))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)


def _fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    qp_ref,
    kp_ref,
    o_ref,
    lse_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    nk: int,
):
    """One (batch, head, q-block, kv-block) grid step.

    Refs: q (block_q, d); k/v (block_k, d); positions qp (block_q, 1) and
    kp (1, block_k) int32 — explicit arrays, not iota, so permuted layouts
    (ring/zigzag shards) mask correctly; o (block_q, d); lse (block_q, 1) —
    scalars-per-row ride as a column, rank-1 tiled outputs fail Mosaic
    lowering (see ops/quantization.py). Scratch acc (block_q, d) f32,
    m/l (block_q, 1) f32 persist across the kv grid axis (innermost,
    sequential on TPU).
    """
    from jax.experimental import pallas as pl

    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_pos = qp_ref[...]  # (block_q, 1)
    k_pos = kp_ref[...]  # (1, block_k)

    # Causal skip: a KV block whose earliest position is beyond this q
    # block's last position is fully masked — skip both matmuls (the grid
    # still visits the step, but the MXU does nothing).
    @pl.when(jnp.min(k_pos) <= jnp.max(q_pos))
    def _update():
        q = q_ref[...]
        k = k_ref[...]
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (block_q, block_k) f32
        scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)

        m_prev = m_ref[...]  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        correction = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)  # (block_q, block_k) f32
        l_ref[...] = l_ref[...] * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * correction + pv
        m_ref[...] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        # Rows whose running max never left the sentinel saw only masked
        # scores: their p = exp(score - m) degenerated to 1 (the classic
        # all-masked-row trap), so acc holds sum-of-V garbage — zero them
        # and pin lse to the sentinel so partial merges weight them out.
        empty = m_ref[...] <= _NEG_INF
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = jnp.where(empty, 0.0, acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[...] = jnp.where(empty, _NEG_INF, m_ref[...] + jnp.log(l))


def _flash_fwd(
    q, k, v, scale, block_q, block_k, interpret,
    q_positions=None, k_positions=None,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    group = h // kv_heads

    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(sq, dtype=jnp.int32), (b, sq))
    if k_positions is None:
        k_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))

    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    # Padded positions are INT32_MAX: beyond every real query, so the
    # causal mask excludes padded KV rows for real queries; padded q rows
    # are sliced off below.
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        # Edge-pad (repeat the last real position), NOT _PAD_POS: padded q
        # rows are sliced off below so their mask content is irrelevant,
        # but an INT32_MAX in the block would defeat the kernel's causal
        # skip (max(q_pos) would dominate every KV block's min).
        q_positions = jnp.pad(q_positions, ((0, 0), (0, pad_q)), mode="edge")
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        k_positions = jnp.pad(
            k_positions, ((0, 0), (0, pad_k)), constant_values=_PAD_POS
        )
    nq = (sq + pad_q) // block_q
    nk = (sk + pad_k) // block_k
    # Positions ride as 3-D so each block is a 2-D tile (a column for q, a
    # row for k — so the in-kernel compare broadcasts without a transpose).
    qp = q_positions.astype(jnp.int32).reshape(b, sq + pad_q, 1)
    kp = k_positions.astype(jnp.int32).reshape(b, 1, sk + pad_k)

    # Kernels run on (b, heads, seq, d): Mosaic requires the last two BLOCK
    # dims be (mult-of-8, mult-of-128-or-whole-dim), so seq and head_dim must
    # be minor. The model-facing (b, seq, heads, d) layout would squeeze the
    # heads dim into second-to-last block position (block 1 vs array h — an
    # on-chip lowering error interpret mode never sees). The transposes are
    # plain XLA copies at the kernel boundary.
    qt = q.transpose(0, 2, 1, 3)  # (b, h, sq_p, d)
    kt = k.transpose(0, 2, 1, 3)  # (b, kv_heads, sk_p, d)
    vt = v.transpose(0, 2, 1, 3)

    kernel = partial(_fwd_kernel, scale=scale, nk=nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec(
                (None, None, block_q, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
            ),
            pl.BlockSpec(
                (None, None, block_k, d),
                lambda ib, ih, iq, ik: (ib, ih // group, ik, 0),
            ),
            pl.BlockSpec(
                (None, None, block_k, d),
                lambda ib, ih, iq, ik: (ib, ih // group, ik, 0),
            ),
            pl.BlockSpec(
                (None, block_q, 1), lambda ib, ih, iq, ik: (ib, iq, 0)
            ),
            pl.BlockSpec(
                (None, 1, block_k), lambda ib, ih, iq, ik: (ib, 0, ik)
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, None, block_q, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
            ),
            pl.BlockSpec(
                (None, None, block_q, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
            ),
        ],
        out_shape=[
            _out_struct((b, h, sq + pad_q, d), q.dtype, (q, k, v, qp, kp)),
            _out_struct((b, h, sq + pad_q, 1), jnp.float32, (q, k, v, qp, kp)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, qp, kp)
    out = out.transpose(0, 2, 1, 3)  # back to (b, sq_p, h, d)
    lse = lse[..., 0].transpose(0, 2, 1)  # (b, sq_p, h)
    if pad_q:
        out = out[:, :sq]
        lse = lse[:, :sq]
    # (b, sq, h) -> (b, sq, kv, group): head h is kv-head h // group, the
    # same layout blockwise_attention's backward expects for its residual.
    return out, lse.reshape(b, sq, kv_heads, group)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, qp_ref, kp_ref,
    dq_ref, dq_acc_ref, *, scale: float, nk: int,
):
    """dQ pass: grid (b, h, nq, nk), KV axis innermost; dq accumulates in
    VMEM scratch across the KV blocks of one q block (FlashAttention-2
    backward, probabilities recomputed from the saved logsumexp)."""
    from jax.experimental import pallas as pl

    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    q_pos = qp_ref[...]  # (block_q, 1)
    k_pos = kp_ref[...]  # (1, block_k)

    @pl.when(jnp.min(k_pos) <= jnp.max(q_pos))
    def _update():
        q = q_ref[...]
        k = k_ref[...]
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (block_q, block_k) f32
        # p from the saved lse; masked entries exactly 0 (also kills padded
        # q rows, whose position is -1 — below every key).
        p = jnp.where(q_pos >= k_pos, jnp.exp(scores - lse_ref[...]), 0.0)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k) f32
        ds = p * (dp - dl_ref[...]) * scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[...] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, qp_ref, kp_ref,
    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale: float, nq: int,
):
    """dK/dV pass: grid (b, h, nk, nq), Q axis innermost; dk/dv accumulate
    in VMEM scratch across the q blocks of one KV block. Outputs are
    PER-Q-HEAD partials (b, sk, h, d) — the GQA group sum happens outside
    so every output block is written exactly once."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    q_pos = qp_ref[...]  # (block_q, 1)
    k_pos = kp_ref[...]  # (1, block_k)

    @pl.when(jnp.max(q_pos) >= jnp.min(k_pos))
    def _update():
        q = q_ref[...]
        k = k_ref[...]
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (block_q, block_k) f32
        p = jnp.where(q_pos >= k_pos, jnp.exp(scores - lse_ref[...]), 0.0)
        do = do_ref[...]
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, d)
        dp = jax.lax.dot_general(
            do, v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl_ref[...]) * scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, d)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[...] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def flash_attention_partial_bwd(
    q, k, v, d_out, out, lse,
    q_positions, k_positions,
    scale, block_q, block_k, interpret,
    delta=None,
    out_dtype=None,
):
    """Fused Pallas backward PARTIAL over an arbitrary KV block: the ring
    backward building block (and, with arange positions, the full causal
    backward). Masking uses explicit global position arrays, so permuted
    (zigzag) ring layouts work; ``lse`` is the GLOBAL logsumexp per q-head
    (b, sq, h) f32 — with it, one call yields this KV block's exact (dk,
    dv) and this query shard's dq contribution, no forward recompute
    (FlashAttention-2 identity).

    ``delta = rowsum(dO*O)`` may be precomputed (ring callers reuse it
    across hops). Returns (dq_partial, dk, dv) in ``out_dtype`` (default
    f32 — ring callers accumulate partials across hops in f32 and cast
    once at the end; the single-block full-causal caller passes the input
    dtype so the kernels cast in VMEM and halve the gradient writeback for
    bf16 models). dk/dv are group-summed. Padding: q rows pad with
    position -1 (below every key → zero contribution to every gradient);
    KV rows pad with _PAD_POS (above every query → likewise zero)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    group = h // kv_heads
    # Same rounding as every forward entry point — block_q to the 16
    # sublane tile, block_k to the 128 LANE tile (the kp position row rides
    # as a (1, block_k) tile whose last dim must be a 128-multiple or the
    # whole dim): ragged blocks pass interpret mode but fail Mosaic
    # lowering on real TPU.
    block_q = min(_next_multiple(int(block_q), 16), _next_multiple(sq, 16))
    block_k = min(_next_multiple(int(block_k), 128), _next_multiple(sk, 128))
    if out_dtype is None:
        out_dtype = jnp.float32

    if delta is None:
        # Cheap elementwise+reduce, XLA fuses it into the surrounding graph.
        delta = jnp.sum(
            d_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        )  # (b, sq, h)

    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        d_out = jnp.pad(d_out, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        lse = jnp.pad(lse, ((0, 0), (0, pad_q), (0, 0)))
        delta = jnp.pad(delta, ((0, 0), (0, pad_q), (0, 0)))
        q_positions = jnp.pad(
            q_positions, ((0, 0), (0, pad_q)), constant_values=-1
        )
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        k_positions = jnp.pad(
            k_positions, ((0, 0), (0, pad_k)), constant_values=_PAD_POS
        )
    nq = (sq + pad_q) // block_q
    nk = (sk + pad_k) // block_k
    qp = q_positions.astype(jnp.int32).reshape(b, sq + pad_q, 1)
    kp = k_positions.astype(jnp.int32).reshape(b, 1, sk + pad_k)
    # Same heads-major transposition as _flash_fwd (see comment there): the
    # kernels see (b, h, seq, d) / (b, h, seq, 1) so seq and d are the block
    # minor dims Mosaic requires.
    qt = q.transpose(0, 2, 1, 3)  # (b, h, sq_p, d)
    kt = k.transpose(0, 2, 1, 3)  # (b, kv_heads, sk_p, d)
    vt = v.transpose(0, 2, 1, 3)
    dot = d_out.transpose(0, 2, 1, 3)  # (b, h, sq_p, d)
    lse_col = lse.reshape(b, sq + pad_q, h, 1).transpose(0, 2, 1, 3)
    delta_col = delta.reshape(b, sq + pad_q, h, 1).transpose(0, 2, 1, 3)

    q_spec = pl.BlockSpec(
        (None, None, block_q, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
    )
    k_spec = pl.BlockSpec(
        (None, None, block_k, d), lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)
    )
    col_spec = pl.BlockSpec(
        (None, None, block_q, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
    )
    qp_spec = pl.BlockSpec((None, block_q, 1), lambda ib, ih, iq, ik: (ib, iq, 0))
    kp_spec = pl.BlockSpec((None, 1, block_k), lambda ib, ih, iq, ik: (ib, 0, ik))
    inputs = (qt, kt, vt, dot, lse_col, delta_col, qp, kp)

    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, scale=scale, nk=nk),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, col_spec, col_spec, qp_spec, kp_spec],
        out_specs=[q_spec],
        out_shape=[_out_struct((b, h, sq + pad_q, d), out_dtype, inputs)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)[0]
    dq = dq.transpose(0, 2, 1, 3)  # (b, sq_p, h, d)

    # dK/dV pass: swap the two inner grid axes (KV outer, Q innermost) so
    # the accumulators persist across q blocks. Index maps take (iq, ik) in
    # swapped positions.
    q_spec_t = pl.BlockSpec(
        (None, None, block_q, d), lambda ib, ih, ik, iq: (ib, ih, iq, 0)
    )
    k_spec_t = pl.BlockSpec(
        (None, None, block_k, d), lambda ib, ih, ik, iq: (ib, ih // group, ik, 0)
    )
    kh_spec_t = pl.BlockSpec(
        (None, None, block_k, d), lambda ib, ih, ik, iq: (ib, ih, ik, 0)
    )
    col_spec_t = pl.BlockSpec(
        (None, None, block_q, 1), lambda ib, ih, ik, iq: (ib, ih, iq, 0)
    )
    qp_spec_t = pl.BlockSpec((None, block_q, 1), lambda ib, ih, ik, iq: (ib, iq, 0))
    kp_spec_t = pl.BlockSpec((None, 1, block_k), lambda ib, ih, ik, iq: (ib, 0, ik))
    dk_h, dv_h = pl.pallas_call(
        partial(_bwd_dkv_kernel, scale=scale, nq=nq),
        grid=(b, h, nk, nq),
        in_specs=[
            q_spec_t, k_spec_t, k_spec_t, q_spec_t, col_spec_t, col_spec_t,
            qp_spec_t, kp_spec_t,
        ],
        out_specs=[kh_spec_t, kh_spec_t],
        out_shape=[
            _out_struct((b, h, sk + pad_k, d), out_dtype, inputs),
            _out_struct((b, h, sk + pad_k, d), out_dtype, inputs),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    dk_h = dk_h.transpose(0, 2, 1, 3)  # (b, sk_p, h, d)
    dv_h = dv_h.transpose(0, 2, 1, 3)

    if pad_q:
        dq = dq[:, :sq]
    if pad_k:
        dk_h = dk_h[:, :sk]
        dv_h = dv_h[:, :sk]
    # GQA group sum of the per-q-head partials (one XLA reduction).
    dk = dk_h.reshape(b, sk, kv_heads, group, d).sum(axis=3)
    dv = dv_h.reshape(b, sk, kv_heads, group, d).sum(axis=3)
    return dq, dk, dv


def _flash_bwd(q, k, v, out, lse, d_out, scale, block_q, block_k, interpret):
    """Full-causal fused backward: the partial backward with arange
    positions and a single all-KV block set."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q_positions = jnp.broadcast_to(jnp.arange(sq, dtype=jnp.int32), (b, sq))
    k_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))
    dq, dk, dv = flash_attention_partial_bwd(
        q, k, v, d_out, out, lse, q_positions, k_positions,
        scale, block_q, block_k, interpret,
        out_dtype=q.dtype,  # no cross-call accumulation: cast in VMEM
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, scale, block_q, block_k, interpret, pallas_bwd):
    return _flash_fwd(q, k, v, scale, block_q, block_k, interpret)[0]


def _flash_core_fwd(q, k, v, scale, block_q, block_k, interpret, pallas_bwd):
    out, lse = _flash_fwd(q, k, v, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(
    scale, block_q, block_k, interpret, pallas_bwd, residuals, d_out
):
    q, k, v, out, lse = residuals
    if pallas_bwd:
        b, s, h, d = q.shape
        # Residual lse is (b, s, kv, group); the kernels index it per
        # q-head h = kvh * group + g — the exact inverse reshape.
        return _flash_bwd(
            q, k, v, out, lse.reshape(b, s, h), d_out,
            scale, block_q, block_k, interpret,
        )
    # Scan-based flash backward (recompute per KV block from the saved
    # logsumexp) — shared with blockwise_attention; the CPU/fallback path.
    return _blockwise_core_bwd(scale, block_k, residuals, d_out)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention_partial(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    k_positions: jnp.ndarray,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
):
    """One causal-attention PARTIAL over an arbitrary KV block: the ring
    attention building block. Masking uses the explicit global position
    arrays (so zigzag/permuted shard layouts work), and the result is
    returned with its logsumexp so partials from different KV shards merge
    exactly (see :func:`merge_attention_partials`).

    Shapes: q (b, sq, h, d); k/v (b, sk, kv_heads, d); positions (b, sq) /
    (b, sk). Returns (out (b, sq, h, d) in q.dtype, lse (b, sq, h) f32;
    fully-masked rows come back as out=0, lse≈-1e30). Forward-only — ring
    callers define their own VJP (ops/ring_attention.py: per-hop
    :func:`flash_attention_partial_bwd` on TPU, einsum ring backward as
    the interpret/CPU fallback).
    """
    b, sq, h, d = q.shape
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = not on_tpu()
    block_q = min(_next_multiple(int(block_q), 16), _next_multiple(sq, 16))
    block_k = min(_next_multiple(int(block_k), 128), _next_multiple(k.shape[1], 128))
    out, lse = _flash_fwd(
        q, k, v, float(scale), block_q, block_k, bool(interpret),
        q_positions=q_positions, k_positions=k_positions,
    )
    return out, lse.reshape(b, sq, h)


def merge_attention_partials(out_a, lse_a, out_b, lse_b):
    """Combines two normalized attention partials of the same queries over
    disjoint KV sets via their logsumexps (the flash/ring merge identity).
    out: (..., d) f32; lse: (...,) f32 with -1e30 as the empty sentinel."""
    m = jnp.maximum(lse_a, lse_b)
    # Guard the both-empty case: exp(-1e30 - -1e30) = 1 would resurrect
    # fully-masked rows with weight 1 each; keep them exactly empty.
    both_empty = m <= _NEG_INF
    wa = jnp.where(both_empty, 0.0, jnp.exp(lse_a - m))
    wb = jnp.where(both_empty, 0.0, jnp.exp(lse_b - m))
    l = wa + wb
    safe_l = jnp.maximum(l, 1e-30)
    out = (out_a * wa[..., None] + out_b * wb[..., None]) / safe_l[..., None]
    lse = jnp.where(both_empty, _NEG_INF, m + jnp.log(safe_l))
    return out, lse


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    use_pallas_bwd: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused causal GQA attention on one device: Pallas forward AND
    FlashAttention-2-style Pallas backward (dq + dkv kernels recomputing
    probabilities from the saved logsumexp).

    Shapes: q (b, s, h, d); k/v (b, s, kv_heads, d); h % kv_heads == 0.
    The sequence is padded to block multiples internally; outputs are
    returned in the original length. Default blocks come from the on-chip
    sweep (scripts/flash_block_sweep.py, TPU v5 lite, 2026-07-31): at
    seq 8192 the original 128x128 ran 91/248 ms fwd / fwd+bwd where
    512x1024 runs 18.8/37.9 ms (4.8x / 6.6x) and 1024x1024 ran 17.4/35.3;
    at seq 2048 the same move is 15.5/25.2 -> 13.0/13.2 ms. Oversized
    blocks clamp to the padded sequence below, so short sequences are
    unaffected. ``interpret=None`` auto-selects
    interpret mode off-TPU so the same call works in CPU tests.
    ``use_pallas_bwd=None`` picks the fused backward exactly when the
    forward compiles (on TPU); CPU tests pass True to exercise the
    backward kernels in interpret mode, and False forces the scan-based
    blockwise fallback.
    """
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    if h % kv_heads:
        raise ValueError(f"n_heads {h} not a multiple of kv_heads {kv_heads}")
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        # Same device-platform check as ops/quantization.py's *_device
        # helpers: the backend NAME on this machine is "axon" while the
        # device platform is "tpu", and only the latter says whether Mosaic
        # can compile the kernel.
        interpret = not on_tpu()
    if use_pallas_bwd is None:
        use_pallas_bwd = not interpret
    # Align the block sizes themselves (not just the clamp bounds):
    # block_q to 16 — the bf16 sublane tile (and a multiple of f32's 8);
    # block_k to 128 — the LANE tile, because the kp position row rides as
    # a (1, block_k) block whose last dim must be a 128-multiple or the
    # whole padded dim. Then clamp oversized blocks to the padded sequence.
    # A ragged block would pass interpret-mode tests and fail Mosaic
    # lowering on the chip (tests/test_mosaic_lowering.py pins this).
    block_q = min(_next_multiple(int(block_q), 16), _next_multiple(s, 16))
    block_k = min(_next_multiple(int(block_k), 128), _next_multiple(s, 128))
    return _flash_core(
        q, k, v, float(scale), int(block_q), int(block_k), bool(interpret),
        bool(use_pallas_bwd),
    )


def _next_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def verify_on_chip() -> dict:
    """Compile (not interpret) the kernel on the attached accelerator and
    check it against dense attention — the CLAUDE.md 'verify kernels on the
    real chip' gate, runnable whenever the relay is healthy:

        python -c "from torchft_tpu.ops.flash_attention import verify_on_chip; print(verify_on_chip())"
    """
    import numpy as np

    from torchft_tpu.models.llama import causal_attention

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        raise RuntimeError(f"no TPU attached (devices()[0] is {dev})")
    b, s, h, kv, d = 2, 256, 4, 2, 64
    kq, kk, kvk = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.bfloat16)
    v = jax.random.normal(kvk, (b, s, kv, d), jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=False)
    ref = causal_attention(q, k, v, scale=d**-0.5)
    err = float(
        jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
    )
    if err > 0.05:  # bf16 tolerance
        raise AssertionError(f"on-chip flash attention mismatch: max err {err}")

    # Backward: compile the fused dq/dkv kernels on-chip and check the
    # gradients against dense attention's.
    def loss_flash(q_, k_, v_):
        return jnp.sum(
            flash_attention(q_, k_, v_, interpret=False, use_pallas_bwd=True)
            .astype(jnp.float32) ** 2
        )

    def loss_dense(q_, k_, v_):
        return jnp.sum(
            causal_attention(q_, k_, v_, scale=d**-0.5).astype(jnp.float32) ** 2
        )

    grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    grads_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    err_bwd = max(
        float(jnp.max(jnp.abs(gf.astype(jnp.float32) - gd.astype(jnp.float32))))
        for gf, gd in zip(grads_flash, grads_dense)
    )
    # Gradients square the bf16 rounding; the scan-backward CPU tests hold
    # the same bound.
    if err_bwd > 0.25:
        raise AssertionError(f"on-chip flash BACKWARD mismatch: max err {err_bwd}")

    # The partial surface (ring building block): explicit PERMUTED position
    # arrays (the (1, block_k) row tile), sq != sk, ragged lengths, a
    # fully-masked hop, and the logsumexp merge — everything the ring path
    # lowers that the full-attention call above does not.
    sq = 200  # ragged: pads to 208
    pos = jax.random.permutation(jax.random.PRNGKey(3), s)[:sq]
    qp = jnp.broadcast_to(pos.astype(jnp.int32), (b, sq))
    kp_full = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    qs = jax.random.normal(kq, (b, sq, h, d), jnp.bfloat16)
    half = s // 2
    o1, l1 = flash_attention_partial(
        qs, k[:, :half], v[:, :half], qp, kp_full[:, :half], interpret=False
    )
    o2, l2 = flash_attention_partial(
        qs, k[:, half:], v[:, half:], qp, kp_full[:, half:], interpret=False
    )
    merged, lse_g = merge_attention_partials(
        o1.astype(jnp.float32), l1, o2.astype(jnp.float32), l2
    )
    # Reference: dense attention with the same permuted-position mask.
    qg = qs.astype(jnp.float32).reshape(b, sq, kv, h // kv, d)
    sc = jnp.einsum("bskgd,btkd->bskgt", qg, k.astype(jnp.float32)) * (d**-0.5)
    mask = qp[:, :, None, None, None] >= kp_full[:, None, None, None, :]
    sc = jnp.where(mask, sc, -1e30)
    pr = jax.nn.softmax(sc, axis=-1)
    ref_p = jnp.einsum("bskgt,btkd->bskgd", pr, v.astype(jnp.float32)).reshape(
        b, sq, h, d
    )
    err_p = float(jnp.max(jnp.abs(merged - ref_p)))
    if err_p > 0.05:
        raise AssertionError(
            f"on-chip flash PARTIAL/merge mismatch: max err {err_p}"
        )

    # The ring-backward building block: flash_attention_partial_bwd
    # compiled with PERMUTED positions, sq != sk, and the global (merged)
    # logsumexp — checked against the FlashAttention-2 einsum identity
    # (the _ring_flash_bwd_scan per-hop math, computed inline).
    d_out_p = jax.random.normal(jax.random.PRNGKey(7), merged.shape, jnp.float32)
    dq_pal, dk_pal, dv_pal = flash_attention_partial_bwd(
        qs, k[:, :half], v[:, :half], d_out_p.astype(qs.dtype),
        merged.astype(qs.dtype), lse_g,
        qp, kp_full[:, :half],
        d**-0.5, 128, 128, False,
    )
    group = h // kv
    qg2 = qs.astype(jnp.float32).reshape(b, sq, kv, group, d)
    dog = d_out_p.reshape(b, sq, kv, group, d)
    og = merged.reshape(b, sq, kv, group, d)
    delta = jnp.sum(dog * og, axis=-1)
    k32 = k[:, :half].astype(jnp.float32)
    v32 = v[:, :half].astype(jnp.float32)
    scores2 = jnp.einsum("bskgd,btkd->bskgt", qg2, k32) * (d**-0.5)
    mask2 = qp[:, :, None, None, None] >= kp_full[:, None, None, None, :half]
    lse_gg = lse_g.reshape(b, sq, kv, group)
    p2 = jnp.where(mask2, jnp.exp(scores2 - lse_gg[..., None]), 0.0)
    dv_ref = jnp.einsum("bskgt,bskgd->btkd", p2, dog)
    dp2 = jnp.einsum("bskgd,btkd->bskgt", dog, v32)
    ds2 = p2 * (dp2 - delta[..., None]) * (d**-0.5)
    dq_ref = jnp.einsum("bskgt,btkd->bskgd", ds2, k32).reshape(b, sq, h, d)
    dk_ref = jnp.einsum("bskgt,bskgd->btkd", ds2, qg2)
    err_pb = max(
        float(jnp.max(jnp.abs(dq_pal.astype(jnp.float32) - dq_ref))),
        float(jnp.max(jnp.abs(dk_pal.astype(jnp.float32) - dk_ref))),
        float(jnp.max(jnp.abs(dv_pal.astype(jnp.float32) - dv_ref))),
    )
    if err_pb > 0.25:
        raise AssertionError(
            f"on-chip flash PARTIAL BACKWARD mismatch: max err {err_pb}"
        )
    return {
        "device": str(dev),
        "max_err": err,
        "max_err_bwd": err_bwd,
        "max_err_partial": err_p,
        "ok": True,
    }
