"""Pallas TPU flash attention: the fused-kernel path for the hot op.

The scan-based :func:`torchft_tpu.ops.ring_attention.blockwise_attention`
already gives O(s·block) memory, but each block update is a separate XLA
fusion: scores, mask, softmax bookkeeping, and the PV matmul round-trip
through HBM between blocks. This module fuses the whole online-softmax
inner loop into ONE Pallas kernel so the accumulators (acc, running max,
running sum) live in VMEM for the duration and the two matmuls per block
ride the MXU back-to-back (pallas_guide.md: grid iterated sequentially on
TPU with the last axis minor, which makes cross-grid-step VMEM scratch the
canonical accumulation pattern).

Scope: forward only. The backward pass reuses the flash-style custom_vjp
backward already verified for ``blockwise_attention`` (recompute
probabilities per block from the saved logsumexp) — the Pallas forward
emits exactly the residuals it needs (out, lse). This keeps the new
Mosaic-lowered surface to one kernel; following ops/quantization.py's
convention it is exercised in interpret mode on CPU tests and compiled on
real TPU. Run :func:`verify_on_chip` on a live chip after any kernel
change (the CLAUDE.md kernel-verification gate); until that has passed on
real hardware, "flash" stays opt-in rather than an "auto" choice.

The reference has no attention code at all (SURVEY.md §2.7: long-sequence
scaling is delegated to torchtitan); this is part of the beyond-reference
long-context stack, sitting below ring attention (which shards the
sequence across chips) as the per-chip kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torchft_tpu.ops.ring_attention import _blockwise_core_bwd

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    block_q: int,
    block_k: int,
    nk: int,
):
    """One (batch, head, q-block, kv-block) grid step.

    Refs: q (block_q, d); k/v (block_k, d); o (block_q, d);
    lse (block_q, 1) — scalars-per-row ride as a column, rank-1 tiled
    outputs fail Mosaic lowering (see ops/quantization.py). Scratch
    acc (block_q, d) f32, m/l (block_q, 1) f32 persist across the kv grid
    axis (innermost, sequential on TPU).
    """
    from jax.experimental import pallas as pl

    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal skip: a KV block whose first position is beyond this q block's
    # last position is fully masked — skip both matmuls (the grid still
    # visits the step, but the MXU does nothing).
    @pl.when(ik * block_k <= iq * block_q + block_q - 1)
    def _update():
        q = q_ref[...]
        k = k_ref[...]
        scores = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (block_q, block_k) f32
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)

        m_prev = m_ref[...]  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        correction = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)  # (block_q, block_k) f32
        l_ref[...] = l_ref[...] * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * correction + pv
        m_ref[...] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[...] = m_ref[...] + jnp.log(l)


def _flash_fwd(q, k, v, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads

    pad_q = (-s) % block_q
    pad_k = (-s) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    if pad_k:
        # Padded KV positions sit beyond every real query, so the causal
        # mask excludes them; padded q rows are sliced off below.
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    nq = (s + pad_q) // block_q
    nk = (s + pad_k) // block_k

    kernel = partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k, nk=nk
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec(
                (None, block_q, None, d), lambda ib, ih, iq, ik: (ib, iq, ih, 0)
            ),
            pl.BlockSpec(
                (None, block_k, None, d),
                lambda ib, ih, iq, ik: (ib, ik, ih // group, 0),
            ),
            pl.BlockSpec(
                (None, block_k, None, d),
                lambda ib, ih, iq, ik: (ib, ik, ih // group, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, block_q, None, d), lambda ib, ih, iq, ik: (ib, iq, ih, 0)
            ),
            pl.BlockSpec(
                (None, block_q, None, 1), lambda ib, ih, iq, ik: (ib, iq, ih, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s + pad_q, h, d), q.dtype),
            jax.ShapeDtypeStruct((b, s + pad_q, h, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    if pad_q:
        out = out[:, :s]
        lse = lse[:, :s]
    # (b, s, h, 1) -> (b, s, kv, group): head h is kv-head h // group, the
    # same layout blockwise_attention's backward expects for its residual.
    return out, lse[..., 0].reshape(b, s, kv_heads, group)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, scale, block_q, block_k, interpret)[0]


def _flash_core_fwd(q, k, v, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(scale, block_q, block_k, interpret, residuals, d_out):
    # The scan-based flash backward (recompute probabilities per KV block
    # from the saved logsumexp) — shared with blockwise_attention, already
    # verified against dense attention gradients.
    return _blockwise_core_bwd(scale, block_k, residuals, d_out)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused causal GQA attention on one device (Pallas TPU kernel forward,
    flash-style recompute backward).

    Shapes: q (b, s, h, d); k/v (b, s, kv_heads, d); h % kv_heads == 0.
    The sequence is padded to block multiples internally; outputs are
    returned in the original length. ``interpret=None`` auto-selects
    interpret mode off-TPU so the same call works in CPU tests.
    """
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    if h % kv_heads:
        raise ValueError(f"n_heads {h} not a multiple of kv_heads {kv_heads}")
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        # Same device-platform check as ops/quantization.py's *_device
        # helpers: the backend NAME on this machine is "axon" while the
        # device platform is "tpu", and only the latter says whether Mosaic
        # can compile the kernel.
        interpret = jax.devices()[0].platform != "tpu"
    # Align the block size itself (not just the clamp bound) to a multiple
    # of 16 — the sublane tile for bf16 (and a multiple of f32's 8) — then
    # clamp oversized blocks to the padded sequence. A ragged block would
    # pass interpret-mode tests and fail Mosaic lowering on the chip.
    block_q = min(_next_multiple(int(block_q), 16), _next_multiple(s, 16))
    block_k = min(_next_multiple(int(block_k), 16), _next_multiple(s, 16))
    return _flash_core(
        q, k, v, float(scale), int(block_q), int(block_k), bool(interpret)
    )


def _next_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def verify_on_chip() -> dict:
    """Compile (not interpret) the kernel on the attached accelerator and
    check it against dense attention — the CLAUDE.md 'verify kernels on the
    real chip' gate, runnable whenever the relay is healthy:

        python -c "from torchft_tpu.ops.flash_attention import verify_on_chip; print(verify_on_chip())"
    """
    import numpy as np

    from torchft_tpu.models.llama import causal_attention

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        raise RuntimeError(f"no TPU attached (devices()[0] is {dev})")
    b, s, h, kv, d = 2, 256, 4, 2, 64
    kq, kk, kvk = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, kv, d), jnp.bfloat16)
    v = jax.random.normal(kvk, (b, s, kv, d), jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=False)
    ref = causal_attention(q, k, v, scale=d**-0.5)
    err = float(
        jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
    )
    if err > 0.05:  # bf16 tolerance
        raise AssertionError(f"on-chip flash attention mismatch: max err {err}")
    return {"device": str(dev), "max_err": err, "ok": True}
