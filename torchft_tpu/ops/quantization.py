"""fp8 (e4m3) block quantization for bandwidth-compressed collectives.

Role-equivalent of the reference's Triton kernels
(/root/reference/torchft/quantization.py): rowwise/blockwise max-abs scales,
fp8e4m3 payloads, and a fused dequantize-reduce-requantize used inside the
quantized allreduce. The TPU build provides:

- a numpy/jnp implementation (works everywhere; used for the host-side TCP
  collective wire format), and
- Pallas TPU kernels for the device-side hot path (``*_pallas``), exercised
  in interpret mode on CPU tests and compiled on real TPU.

Layout: arrays are flattened, padded to a multiple of ``block``, and viewed
as ``(n_blocks, block)``; each block carries one float32 scale. The wire
payload is ``scales || fp8 payload``, mirroring the reference's interleaved
[scales||payload] slices.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import ml_dtypes
import numpy as np

__all__ = [
    "BLOCK",
    "FP8_MAX",
    "quantize_blocks",
    "dequantize_blocks",
    "reduce_quantized",
    "pack_arrays",
    "unpack_arrays",
    "quantize_blocks_pallas",
    "dequantize_blocks_pallas",
]

BLOCK = 256
FP8_MAX = 448.0  # float8_e4m3fn dynamic range
_FP8 = ml_dtypes.float8_e4m3fn


def _as_blocks(flat: np.ndarray, block: int = BLOCK) -> np.ndarray:
    pad = (-flat.size) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, dtype=flat.dtype)])
    return flat.reshape(-1, block)


def quantize_blocks(
    array: np.ndarray, block: int = BLOCK
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (payload fp8 (n_blocks, block), scales f32 (n_blocks,))."""
    flat = np.ascontiguousarray(array).astype(np.float32).reshape(-1)
    blocks = _as_blocks(flat, block)
    maxabs = np.max(np.abs(blocks), axis=1)
    scales = np.where(maxabs > 0, maxabs / FP8_MAX, 1.0).astype(np.float32)
    payload = (blocks / scales[:, None]).astype(_FP8)
    return payload, scales


def dequantize_blocks(
    payload: np.ndarray, scales: np.ndarray, shape: Tuple[int, ...], dtype: np.dtype
) -> np.ndarray:
    """Inverse of :func:`quantize_blocks` (drops padding)."""
    blocks = payload.astype(np.float32) * scales[:, None]
    size = int(np.prod(shape))
    return blocks.reshape(-1)[:size].reshape(shape).astype(dtype)


def reduce_quantized(
    payloads: Sequence[np.ndarray], scales: Sequence[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Fused dequantize-sum-requantize over per-rank quantized chunks
    (reference fused_reduce_fp8): accumulates in float32, emits fresh fp8
    payload + scales for the reduced result."""
    acc = payloads[0].astype(np.float32) * scales[0][:, None]
    for payload, scale in zip(payloads[1:], scales[1:]):
        acc += payload.astype(np.float32) * scale[:, None]
    maxabs = np.max(np.abs(acc), axis=1)
    out_scales = np.where(maxabs > 0, maxabs / FP8_MAX, 1.0).astype(np.float32)
    out_payload = (acc / out_scales[:, None]).astype(_FP8)
    return out_payload, out_scales


def pack_arrays(payload: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Packs [scales || payload] into one uint8 wire buffer."""
    return np.concatenate(
        [scales.astype(np.float32).view(np.uint8).reshape(-1),
         payload.view(np.uint8).reshape(-1)]
    )


def unpack_arrays(buf: np.ndarray, n_blocks: int, block: int = BLOCK) -> Tuple[np.ndarray, np.ndarray]:
    scale_bytes = n_blocks * 4
    scales = buf[:scale_bytes].view(np.float32).copy()
    payload = buf[scale_bytes : scale_bytes + n_blocks * block].view(_FP8).reshape(
        n_blocks, block
    ).copy()
    return payload, scales


# ---------------------------------------------------------------------------
# Pallas TPU kernels (device-side hot path)
# ---------------------------------------------------------------------------


def quantize_blocks_pallas(x, block: int = BLOCK, interpret: bool = False):
    """Device-side blockwise fp8 quantization.

    ``x``: float array, flattened/padded by the caller to (n_blocks, block).
    Returns (payload fp8, scales f32). One grid row per block tile keeps the
    VPU busy while scales stay in SMEM-sized slices.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_blocks = x.shape[0]
    rows_per_tile = min(n_blocks, 8)

    def kernel(x_ref, payload_ref, scales_ref):
        block_data = x_ref[:].astype(jnp.float32)
        maxabs = jnp.max(jnp.abs(block_data), axis=1, keepdims=True)
        scale = jnp.where(maxabs > 0, maxabs / FP8_MAX, 1.0)
        scales_ref[:] = scale
        payload_ref[:] = (block_data / scale).astype(jnp.float8_e4m3fn)

    grid = ((n_blocks + rows_per_tile - 1) // rows_per_tile,)
    payload, scales = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0)),
            # Scales ride as a (n_blocks, 1) column so the block layout obeys
            # TPU tiling (rank-1 dynamic slices are not 128-aligned here).
            pl.BlockSpec((rows_per_tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, block), jnp.float8_e4m3fn),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x)
    return payload, scales.reshape(n_blocks)


def dequantize_blocks_pallas(payload, scales, interpret: bool = False):
    """Device-side blockwise fp8 dequantization to float32."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_blocks, block = payload.shape
    rows_per_tile = min(n_blocks, 8)

    def kernel(payload_ref, scales_ref, out_ref):
        out_ref[:] = payload_ref[:].astype(jnp.float32) * scales_ref[:]

    grid = ((n_blocks + rows_per_tile - 1) // rows_per_tile,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0)),
            pl.BlockSpec((rows_per_tile, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_blocks, block), jnp.float32),
        interpret=interpret,
    )(payload, scales.reshape(n_blocks, 1))


def quantize_blocks_device(x, block: int = BLOCK):
    """Device-side quantization of a flat array: pads to a block multiple,
    returns (payload fp8 (n_blocks, block), scales f32 (n_blocks,)). Uses the
    Pallas kernel on TPU, a jitted jnp path elsewhere."""
    import jax
    import jax.numpy as jnp

    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, dtype=flat.dtype)])
    blocks = flat.reshape(-1, block).astype(jnp.float32)
    if jax.devices()[0].platform == "tpu":
        return quantize_blocks_pallas(blocks, block)
    maxabs = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(maxabs > 0, maxabs / FP8_MAX, 1.0).astype(jnp.float32)
    payload = (blocks / scales[:, None]).astype(jnp.float8_e4m3fn)
    return payload, scales


def dequantize_blocks_device(payload, scales):
    """Device-side dequantization to a flat f32 array (padding retained)."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform == "tpu":
        out = dequantize_blocks_pallas(payload, scales)
    else:
        out = payload.astype(jnp.float32) * scales[:, None]
    return out.reshape(-1)


def make_tree_fp8_codec(leaves):
    """Builds a jitted (quantize, dequantize) pair for a fixed list of float
    array leaves: quantize concatenates the leaves and emits (payload,
    scales); dequantize inverts back to per-leaf arrays with the original
    shapes/dtypes. Shared by the DDP and DiLoCo fp8 device pipelines."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    for leaf in leaves:
        if np.dtype(leaf.dtype).kind not in ("f", "V"):
            raise TypeError(
                f"fp8 quantized sync requires float leaves, got {leaf.dtype}; "
                "use the unquantized path for integer state"
            )
    sizes = [int(np.prod(leaf.shape)) for leaf in leaves]
    shapes = [tuple(leaf.shape) for leaf in leaves]
    dtypes = [leaf.dtype for leaf in leaves]
    total = sum(sizes)
    offsets = np.cumsum([0] + sizes)

    def quantize(leaves_in):
        flat = jnp.concatenate(
            [leaf.astype(jnp.float32).reshape(-1) for leaf in leaves_in]
        )
        return quantize_blocks_device(flat)

    def dequantize(payload, scales):
        flat = dequantize_blocks_device(payload, scales)[:total]
        return [
            flat[offsets[i] : offsets[i + 1]].reshape(shapes[i]).astype(dtypes[i])
            for i in range(len(sizes))
        ]

    return jax.jit(quantize), jax.jit(dequantize)


def verify_on_chip() -> dict:
    """Compile (not interpret) the Pallas fp8 kernels on the attached TPU
    and check them against the host reference codec — the CLAUDE.md
    'verify kernels on the real chip' gate, automated like
    flash_attention.verify_on_chip:

        python -c "from torchft_tpu.ops.quantization import verify_on_chip; print(verify_on_chip())"
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        raise RuntimeError(f"no TPU attached (devices()[0] is {dev})")

    # Ragged length forces the padding path; mixed magnitudes + an all-zero
    # block exercise the scale selection.
    rng = np.random.default_rng(0)
    host = np.concatenate(
        [
            rng.normal(0, 3.0, 700).astype(np.float32),
            np.zeros(BLOCK, np.float32),
            rng.normal(0, 1e-4, 500).astype(np.float32),
        ]
    )
    x = jnp.asarray(host)
    payload, scales = jax.jit(quantize_blocks_device)(x)
    out = jax.jit(dequantize_blocks_device)(payload, scales)[: host.size]

    ref_payload, ref_scales = quantize_blocks(host)
    ref = dequantize_blocks(ref_payload, ref_scales, host.shape, host.dtype)

    # The kernel must round-trip as accurately as the host codec (both are
    # bounded by fp8 e4m3 resolution: ~2^-3 relative per block max).
    err_chip = float(np.max(np.abs(np.asarray(out) - host)))
    err_host = float(np.max(np.abs(ref - host)))
    if err_chip > max(err_host * 1.5, 1e-6):
        raise AssertionError(
            f"on-chip fp8 codec error {err_chip} vs host reference {err_host}"
        )
    # Wire-format compatibility: the device payload must dequantize with the
    # HOST kernels too (the mixed device/host paths share one format).
    mixed = dequantize_blocks(
        np.asarray(payload).view(_FP8),
        np.asarray(scales).astype(np.float32),
        host.shape,
        host.dtype,
    )
    err_mixed = float(np.max(np.abs(mixed - np.asarray(out))))
    if err_mixed > 1e-6:
        raise AssertionError(f"device payload diverges from host decode: {err_mixed}")
    return {"ok": True, "max_err": err_chip, "host_err": err_host}
