"""Block quantization (fp8/int8/int4) for bandwidth-compressed collectives.

Role-equivalent of the reference's Triton kernels
(/root/reference/torchft/quantization.py): rowwise/blockwise max-abs scales,
8-bit payloads, and a fused dequantize-reduce-requantize used inside the
quantized allreduce. Like the reference — which emits fp8e4nv on SM90+ and
int8 on older GPUs — the wire formats share one layout:

- ``"fp8"`` (float8_e4m3): wider per-block dynamic range;
- ``"int8"``: symmetric round-to-nearest, finer resolution near the block
  max and universally fast integer hardware;
- ``"int4"`` (beyond reference): symmetric [-7, 7] nibbles packed two per
  byte — HALF the wire bytes of the 8-bit formats. The cross-DCN outer
  syncs (DiLoCo pseudogradients) are the intended user; at 4 bits the
  per-block resolution is coarse, so it is opt-in, never the default.

Select per call or globally via ``TPUFT_WIRE_DTYPE``. The TPU build
provides a numpy/jnp implementation (works everywhere; used for the
host-side TCP collective wire format) and Pallas TPU kernels for the
device-side hot path (``*_pallas``), exercised in interpret mode on CPU
tests and compiled on real TPU. int4 uses the jnp device path on every
backend (nibble packing is plain XLA integer ops; no Pallas kernel).

Layout: arrays are flattened, padded to a multiple of ``block``, and viewed
as ``(n_blocks, block)``; each block carries one float32 scale. The wire
payload is ``scales || payload``, mirroring the reference's interleaved
[scales||payload] slices. The 8-bit formats are 1 byte/element and int4
is a packed uint8 ``(n_blocks, block // 2)``; ``payload_cols()`` gives the
per-block wire width, and the payload dtype rides in the arrays so every
consumer (dequantize, reduce, unpack) dispatches on it.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import ml_dtypes
import numpy as np

from torchft_tpu.utils.platform import on_tpu

__all__ = [
    "BLOCK",
    "FP8_MAX",
    "INT8_MAX",
    "INT4_MAX",
    "WIRE_DTYPE_ENV",
    "default_wire",
    "wire_of",
    "payload_cols",
    "quantize_blocks",
    "dequantize_blocks",
    "reduce_quantized",
    "pack_arrays",
    "unpack_arrays",
    "quantize_blocks_pallas",
    "dequantize_blocks_pallas",
]

BLOCK = 256
FP8_MAX = 448.0  # float8_e4m3fn dynamic range
INT8_MAX = 127.0
INT4_MAX = 7.0  # symmetric nibbles: [-7, 7], -8 never produced
_FP8 = ml_dtypes.float8_e4m3fn
WIRE_DTYPE_ENV = "TPUFT_WIRE_DTYPE"

# int4's payload is nibble-packed into uint8 — a dtype neither 8-bit
# format uses, so dtype-dispatch (wire_of) stays unambiguous.
_WIRE_NP_DTYPES = {
    "fp8": np.dtype(_FP8),
    "int8": np.dtype(np.int8),
    "int4": np.dtype(np.uint8),
}
_WIRE_QMAX = {"fp8": FP8_MAX, "int8": INT8_MAX, "int4": INT4_MAX}


def payload_cols(wire: str, block: int = BLOCK) -> int:
    """Per-block wire payload width in bytes (int4 packs two per byte)."""
    if wire == "int4" and block % 2:
        raise ValueError(f"int4 requires an even block size, got {block}")
    return block // 2 if wire == "int4" else block


def _pack_int4_np(v: np.ndarray) -> np.ndarray:
    """(n, block) int8 in [-7, 7] -> (n, block//2) uint8, low nibble first."""
    u = v.astype(np.uint8) & 0xF
    return (u[:, 0::2] | (u[:, 1::2] << 4)).astype(np.uint8)


def _unpack_int4_np(p: np.ndarray) -> np.ndarray:
    """Inverse of :func:`_pack_int4_np` with 4-bit sign extension."""
    lo = p & 0xF
    hi = (p >> 4) & 0xF
    out = np.empty((p.shape[0], p.shape[1] * 2), np.uint8)
    out[:, 0::2] = lo
    out[:, 1::2] = hi
    return ((out.astype(np.int16) ^ 8) - 8).astype(np.int8)


def _resolve_wire(wire: "Optional[str]") -> str:
    """Validates an explicit wire choice; None means the env default."""
    if wire is None:
        return default_wire()
    if wire not in _WIRE_NP_DTYPES:
        raise ValueError(
            f"wire={wire!r} is not one of {sorted(_WIRE_NP_DTYPES)}"
        )
    return wire


def default_wire() -> str:
    """The process-wide wire format: ``TPUFT_WIRE_DTYPE`` or ``"fp8"``."""
    wire = os.environ.get(WIRE_DTYPE_ENV, "fp8")
    if wire not in _WIRE_NP_DTYPES:
        raise ValueError(
            f"{WIRE_DTYPE_ENV}={wire!r} is not one of {sorted(_WIRE_NP_DTYPES)}"
        )
    return wire


def wire_of(payload) -> str:
    """Wire format of an existing payload array, by dtype."""
    dtype = np.dtype(payload.dtype)
    for name, np_dtype in _WIRE_NP_DTYPES.items():
        if dtype == np_dtype:
            return name
    raise TypeError(f"array dtype {dtype} is not a known wire payload format")


def _as_blocks(flat: np.ndarray, block: int = BLOCK) -> np.ndarray:
    pad = (-flat.size) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, dtype=flat.dtype)])
    return flat.reshape(-1, block)


def quantize_blocks(
    array: np.ndarray, block: int = BLOCK, wire: Optional[str] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (payload (n_blocks, payload_cols(wire)), scales f32
    (n_blocks,)) — 1 byte/element for fp8/int8, nibble-packed uint8 at
    block//2 bytes for int4."""
    wire = _resolve_wire(wire)
    flat = np.ascontiguousarray(array).astype(np.float32).reshape(-1)
    blocks = _as_blocks(flat, block)
    maxabs = np.max(np.abs(blocks), axis=1)
    scales = np.where(maxabs > 0, maxabs / _WIRE_QMAX[wire], 1.0).astype(np.float32)
    scaled = blocks / scales[:, None]
    if wire == "int8":
        scaled = np.rint(scaled)
    elif wire == "int4":
        payload_cols(wire, block)  # validates even block
        return _pack_int4_np(np.rint(scaled).astype(np.int8)), scales
    payload = scaled.astype(_WIRE_NP_DTYPES[wire])
    return payload, scales


def _decode_payload_np(payload: np.ndarray) -> np.ndarray:
    """Payload -> f32 block values (unpacks int4 by dtype dispatch)."""
    if payload.dtype == np.uint8:
        payload = _unpack_int4_np(payload)
    return payload.astype(np.float32)


def dequantize_blocks(
    payload: np.ndarray, scales: np.ndarray, shape: Tuple[int, ...], dtype: np.dtype
) -> np.ndarray:
    """Inverse of :func:`quantize_blocks` (drops padding)."""
    blocks = _decode_payload_np(payload) * scales[:, None]
    size = int(np.prod(shape))
    return blocks.reshape(-1)[:size].reshape(shape).astype(dtype)


def reduce_quantized(
    payloads: Sequence[np.ndarray], scales: Sequence[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Fused dequantize-sum-requantize over per-rank quantized chunks
    (reference fused_reduce_fp8): accumulates in float32, emits a fresh
    payload + scales for the reduced result in the inputs' wire format."""
    wire = wire_of(payloads[0])
    acc = _decode_payload_np(payloads[0]) * scales[0][:, None]
    for payload, scale in zip(payloads[1:], scales[1:]):
        acc += _decode_payload_np(payload) * scale[:, None]
    maxabs = np.max(np.abs(acc), axis=1)
    out_scales = np.where(maxabs > 0, maxabs / _WIRE_QMAX[wire], 1.0).astype(
        np.float32
    )
    out = acc / out_scales[:, None]
    if wire == "int8":
        out = np.rint(out)
    elif wire == "int4":
        return _pack_int4_np(np.rint(out).astype(np.int8)), out_scales
    out_payload = out.astype(_WIRE_NP_DTYPES[wire])
    return out_payload, out_scales


_WIRE_TAGS = {"fp8": 0, "int8": 1, "int4": 2}
_TAG_WIRES = {tag: name for name, tag in _WIRE_TAGS.items()}

# One leading byte identifies the payload format on the wire. The 8-bit
# formats are byte-identical in size, so without it a cross-rank
# TPUFT_WIRE_DTYPE disagreement would decode peers' fp8 bits as int8 and
# silently corrupt the reduction; the tag turns that into a hard error.
WIRE_HEADER_BYTES = 1


def pack_arrays(payload: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Packs [format tag || scales || payload] into one uint8 wire buffer."""
    tag = np.array([_WIRE_TAGS[wire_of(payload)]], dtype=np.uint8)
    return np.concatenate(
        [tag,
         scales.astype(np.float32).view(np.uint8).reshape(-1),
         payload.view(np.uint8).reshape(-1)]
    )


def unpack_arrays(
    buf: np.ndarray, n_blocks: int, block: int = BLOCK, wire: Optional[str] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`pack_arrays`. The embedded format tag is
    authoritative; passing ``wire`` asserts the peer used the expected
    format (raising on cross-rank TPUFT_WIRE_DTYPE disagreement)."""
    tag_wire = _TAG_WIRES.get(int(buf[0]))
    if tag_wire is None:
        raise ValueError(f"unknown wire format tag {int(buf[0])} in buffer")
    if wire is not None and wire != tag_wire:
        raise ValueError(
            f"wire format mismatch: peer sent {tag_wire!r}, this rank expects "
            f"{wire!r} — TPUFT_WIRE_DTYPE must agree across all replicas"
        )
    body = buf[WIRE_HEADER_BYTES:]
    scale_bytes = n_blocks * 4
    scales = body[:scale_bytes].view(np.float32).copy()
    cols = payload_cols(tag_wire, block)
    payload = (
        body[scale_bytes : scale_bytes + n_blocks * cols]
        .view(_WIRE_NP_DTYPES[tag_wire])
        .reshape(n_blocks, cols)
        .copy()
    )
    return payload, scales


# ---------------------------------------------------------------------------
# Pallas TPU kernels (device-side hot path)
# ---------------------------------------------------------------------------

# Grid tile height shared by the paired quantize/dequantize kernels (they
# must stay in sync — a mismatch silently changes the partial-final-tile
# shape between the two directions). Rows are independent, so the limits
# are VMEM (1024 x 256 f32 = 1 MB/tile, double-buffered — well inside the
# ~16 MB budget) and Mosaic tiling (1024 is a multiple of the 8-bit
# payload's 32-row tile; a smaller n_blocks rides whole-dim via min()).
# The original 8-row tiles made a 256 MB codec run a 32k-step grid whose
# per-step overhead capped it at ~12 GB/s on a v5e (KERNEL_BENCH_TPU first
# capture); 1024-row tiles measure ~19 GB/s, above the fused XLA path.
_ROWS_PER_TILE = 1024


def quantize_blocks_pallas(
    x,
    block: int = BLOCK,
    interpret: bool = False,
    wire: Optional[str] = None,
    rows_per_tile: Optional[int] = None,
):
    """Device-side blockwise 8-bit quantization (fp8 or int8).

    ``x``: float array, flattened/padded by the caller to (n_blocks, block).
    Returns (payload, scales f32). One grid row per block tile keeps the
    VPU busy while scales stay in SMEM-sized slices. ``rows_per_tile``
    overrides the tuned grid tile height (:data:`_ROWS_PER_TILE`) — the
    free parameter ``scripts/codec_block_sweep.py`` sweeps on-chip.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    wire = _resolve_wire(wire)
    if wire == "int4":
        raise ValueError(
            "int4 has no Pallas kernel — use quantize_blocks_device (jnp path)"
        )
    qmax = _WIRE_QMAX[wire]
    out_dtype = jnp.int8 if wire == "int8" else jnp.float8_e4m3fn
    n_blocks = x.shape[0]
    rows_per_tile = min(
        n_blocks, rows_per_tile if rows_per_tile else _ROWS_PER_TILE
    )

    def kernel(x_ref, payload_ref, scales_ref):
        block_data = x_ref[:].astype(jnp.float32)
        maxabs = jnp.max(jnp.abs(block_data), axis=1, keepdims=True)
        scale = jnp.where(maxabs > 0, maxabs / qmax, 1.0)
        scales_ref[:] = scale
        scaled = block_data / scale
        if wire == "int8":
            scaled = jnp.round(scaled)
        payload_ref[:] = scaled.astype(out_dtype)

    grid = ((n_blocks + rows_per_tile - 1) // rows_per_tile,)
    payload, scales = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0)),
            # Scales ride as a (n_blocks, 1) column so the block layout obeys
            # TPU tiling (rank-1 dynamic slices are not 128-aligned here).
            pl.BlockSpec((rows_per_tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, block), out_dtype),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x)
    return payload, scales.reshape(n_blocks)


def dequantize_blocks_pallas(
    payload, scales, interpret: bool = False, rows_per_tile: Optional[int] = None
):
    """Device-side blockwise fp8/int8 dequantization to float32.
    ``rows_per_tile`` as in :func:`quantize_blocks_pallas` (the paired
    kernels need not share a height — the wire format is tile-agnostic)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if payload.dtype == jnp.uint8:
        raise ValueError(
            "packed int4 has no Pallas kernel — use dequantize_blocks_device"
        )
    n_blocks, block = payload.shape
    rows_per_tile = min(
        n_blocks, rows_per_tile if rows_per_tile else _ROWS_PER_TILE
    )

    def kernel(payload_ref, scales_ref, out_ref):
        out_ref[:] = payload_ref[:].astype(jnp.float32) * scales_ref[:]

    grid = ((n_blocks + rows_per_tile - 1) // rows_per_tile,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0)),
            pl.BlockSpec((rows_per_tile, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows_per_tile, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_blocks, block), jnp.float32),
        interpret=interpret,
    )(payload, scales.reshape(n_blocks, 1))


def quantize_blocks_device(x, block: int = BLOCK, wire: Optional[str] = None):
    """Device-side quantization of a flat array: pads to a block multiple,
    returns (payload (n_blocks, payload_cols(wire)), scales f32
    (n_blocks,)). Uses the Pallas kernel on TPU (fp8/int8), a jitted jnp
    path elsewhere and for packed int4."""
    import jax
    import jax.numpy as jnp

    wire = _resolve_wire(wire)
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, dtype=flat.dtype)])
    blocks = flat.reshape(-1, block).astype(jnp.float32)
    if on_tpu() and wire != "int4":
        return quantize_blocks_pallas(blocks, block, wire=wire)
    maxabs = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(maxabs > 0, maxabs / _WIRE_QMAX[wire], 1.0).astype(
        jnp.float32
    )
    scaled = blocks / scales[:, None]
    if wire == "int8":
        scaled = jnp.round(scaled)
    elif wire == "int4":
        # Nibble-pack on device: plain XLA integer ops, no Pallas kernel.
        u = jnp.round(scaled).astype(jnp.int8).astype(jnp.uint8) & 0xF
        return (u[:, 0::2] | (u[:, 1::2] << 4)).astype(jnp.uint8), scales
    payload = scaled.astype(jnp.int8 if wire == "int8" else jnp.float8_e4m3fn)
    return payload, scales


def dequantize_blocks_device(payload, scales):
    """Device-side dequantization to a flat f32 array (padding retained)."""
    import jax
    import jax.numpy as jnp

    if payload.dtype == jnp.uint8:  # packed int4: unpack with sign extension
        lo = payload & 0xF
        hi = (payload >> 4) & 0xF
        both = jnp.stack([lo, hi], axis=-1).reshape(payload.shape[0], -1)
        vals = (both.astype(jnp.int16) ^ 8) - 8
        out = vals.astype(jnp.float32) * scales[:, None]
    elif on_tpu():
        out = dequantize_blocks_pallas(payload, scales)
    else:
        out = payload.astype(jnp.float32) * scales[:, None]
    return out.reshape(-1)


def make_tree_fp8_codec(leaves, wire: Optional[str] = None):
    """Builds a jitted (quantize, dequantize) pair for a fixed list of float
    array leaves: quantize concatenates the leaves and emits (payload,
    scales); dequantize inverts back to per-leaf arrays with the original
    shapes/dtypes. Shared by the DDP and DiLoCo quantized device pipelines;
    ``wire`` picks the payload format (default: ``TPUFT_WIRE_DTYPE``/fp8 —
    the name keeps the historical "fp8" even though int8 is also valid)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    wire = _resolve_wire(wire)
    for leaf in leaves:
        if np.dtype(leaf.dtype).kind not in ("f", "V"):
            raise TypeError(
                f"quantized sync requires float leaves, got {leaf.dtype}; "
                "use the unquantized path for integer state"
            )
    sizes = [int(np.prod(leaf.shape)) for leaf in leaves]
    shapes = [tuple(leaf.shape) for leaf in leaves]
    dtypes = [leaf.dtype for leaf in leaves]
    total = sum(sizes)
    offsets = np.cumsum([0] + sizes)

    def quantize(leaves_in):
        flat = jnp.concatenate(
            [leaf.astype(jnp.float32).reshape(-1) for leaf in leaves_in]
        )
        return quantize_blocks_device(flat, wire=wire)

    def dequantize(payload, scales):
        flat = dequantize_blocks_device(payload, scales)[:total]
        return [
            flat[offsets[i] : offsets[i + 1]].reshape(shapes[i]).astype(dtypes[i])
            for i in range(len(sizes))
        ]

    return jax.jit(quantize), jax.jit(dequantize)


def verify_on_chip() -> dict:
    """Compile (not interpret) the Pallas codec kernels on the attached TPU
    — every wire format — and check them against the host reference codec:
    the CLAUDE.md 'verify kernels on the real chip' gate, automated like
    flash_attention.verify_on_chip:

        python -c "from torchft_tpu.ops.quantization import verify_on_chip; print(verify_on_chip())"
    """
    import jax
    import numpy as np

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        raise RuntimeError(f"no TPU attached (devices()[0] is {dev})")

    # Ragged length forces the padding path; mixed magnitudes + an all-zero
    # block exercise the scale selection. The second, larger length lands
    # on 1200 blocks — past _ROWS_PER_TILE with a partial final grid tile —
    # so the retiled kernels' ragged-grid branch is numerically verified on
    # the compiled Mosaic path too, not just interpret mode + the chipless
    # lowering gate.
    rng = np.random.default_rng(0)
    host_small = np.concatenate(
        [
            rng.normal(0, 3.0, 700).astype(np.float32),
            np.zeros(BLOCK, np.float32),
            rng.normal(0, 1e-4, 500).astype(np.float32),
        ]
    )
    host_ragged = rng.normal(0, 2.0, 1200 * BLOCK - 37).astype(np.float32)
    result: dict = {"ok": True}
    for label, host in (("small", host_small), ("ragged", host_ragged)):
        _verify_roundtrips(host, result, label)
    return result


def _verify_roundtrips(host, result: dict, label: str) -> None:
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.asarray(host)
    for wire in _WIRE_NP_DTYPES:
        payload, scales = jax.jit(
            functools.partial(quantize_blocks_device, wire=wire)
        )(x)
        out = jax.jit(dequantize_blocks_device)(payload, scales)[: host.size]

        ref_payload, ref_scales = quantize_blocks(host, wire=wire)
        ref = dequantize_blocks(ref_payload, ref_scales, host.shape, host.dtype)

        # The kernel must round-trip as accurately as the host codec (both
        # are bounded by the 8-bit format's per-block resolution).
        err_chip = float(np.max(np.abs(np.asarray(out) - host)))
        err_host = float(np.max(np.abs(ref - host)))
        if err_chip > max(err_host * 1.5, 1e-6):
            raise AssertionError(
                f"on-chip {wire} codec error {err_chip} vs host {err_host}"
            )
        # Wire-format compatibility: the device payload must dequantize with
        # the HOST kernels too (the mixed device/host paths share one
        # format).
        mixed = dequantize_blocks(
            np.asarray(payload).view(_WIRE_NP_DTYPES[wire]),
            np.asarray(scales).astype(np.float32),
            host.shape,
            host.dtype,
        )
        err_mixed = float(np.max(np.abs(mixed - np.asarray(out))))
        if err_mixed > 1e-6:
            raise AssertionError(
                f"device {wire} payload diverges from host decode: {err_mixed}"
            )
        # Per-pass keys so the committed artifact records BOTH passes (the
        # ragged multi-tile pass used to overwrite the small mixed-
        # magnitude one); the unlabeled legacy key stays as the worst case
        # across passes so existing artifact readers keep a meaningful
        # number.
        result[f"{wire}_max_err_{label}"] = err_chip
        result[f"{wire}_host_err_{label}"] = err_host
        result[f"{wire}_max_err"] = max(result.get(f"{wire}_max_err", 0.0), err_chip)
        result[f"{wire}_host_err"] = max(result.get(f"{wire}_host_err", 0.0), err_host)
