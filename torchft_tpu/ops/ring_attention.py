"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context is first-class in this framework even though the reference has
no context-parallel code (SURVEY.md §2.7: absent; the FT replica axis stays
orthogonal so a CP/ring axis fits inside the slice). Design follows the
blockwise/ring attention literature (Liu et al., https://arxiv.org/abs/2310.01889):

Each device in the ``sp`` axis holds one sequence shard of Q, K, V. K/V
blocks rotate around the ring via ``jax.lax.ppermute`` while every device
accumulates attention for its local Q block with an **online softmax**
(running max + normalizer, flash-attention style), so the full sequence
never materializes on one chip. Causality is enforced per ring step by
comparing global position ids — a shard attends to a rotated KV block only
where q_pos >= k_pos, which also makes the code correct for any sequence
layout (contiguous shards being the standard one).

Use inside shard_map/jit over a mesh with the ``sp`` axis, activations
sharded (batch, seq/sp, heads, head_dim). Compute rides the MXU per block;
ICI traffic is one KV block per step, overlapped by XLA with the block
matmuls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30


def _block_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    k_pos: jnp.ndarray,
    scale: float,
    acc: jnp.ndarray,
    row_max: jnp.ndarray,
    row_sum: jnp.ndarray,
):
    """One flash-style block update.

    q: (b, sq, kv, g, d); k/v: (b, sk, kv, d); positions (b, sq)/(b, sk).
    acc: (b, sq, kv, g, d) f32; row_max/row_sum: (b, sq, kv, g) f32.
    """
    scores = jnp.einsum("bskgd,btkd->bskgt", q, k).astype(jnp.float32) * scale
    causal = q_pos[:, :, None, None, None] >= k_pos[:, None, None, None, :]
    scores = jnp.where(causal, scores, _NEG_INF)

    block_max = jnp.max(scores, axis=-1)
    new_max = jnp.maximum(row_max, block_max)
    # Rescale the old accumulator to the new max.
    correction = jnp.exp(row_max - new_max)
    probs = jnp.exp(scores - new_max[..., None])
    new_sum = row_sum * correction + jnp.sum(probs, axis=-1)
    block_out = jnp.einsum("bskgt,btkd->bskgd", probs.astype(v.dtype), v).astype(
        jnp.float32
    )
    new_acc = acc * correction[..., None] + block_out
    return new_acc, new_max, new_sum


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    scale: Optional[float] = None,
    q_positions: Optional[jnp.ndarray] = None,
    k_positions: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Causal GQA attention with K/V rotating over ``axis_name``.

    Call from inside shard_map (or jit-with-sharding) where the seq dim of
    q/k/v is the per-device shard. Shapes: q (b, s_local, h, d);
    k/v (b, s_local, kv_heads, d). Positions default to contiguous shards
    ordered by the device's axis index.
    """
    axis_size = jax.lax.psum(1, axis_name)
    axis_index = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    if scale is None:
        scale = d**-0.5

    if q_positions is None:
        base = axis_index * s_local
        q_positions = jnp.broadcast_to(base + jnp.arange(s_local), (b, s_local))
    if k_positions is None:
        k_positions = q_positions

    qg = q.reshape(b, s_local, kv_heads, group, d)
    acc = jnp.zeros((b, s_local, kv_heads, group, d), dtype=jnp.float32)
    row_max = jnp.full((b, s_local, kv_heads, group), _NEG_INF, dtype=jnp.float32)
    row_sum = jnp.zeros((b, s_local, kv_heads, group), dtype=jnp.float32)
    # The constant-initialized carries must be marked varying over the ring
    # axis or the fori_loop carry types mismatch under shard_map's
    # varying-manual-axes checking.
    if hasattr(jax.lax, "pcast"):
        acc, row_max, row_sum = (
            jax.lax.pcast(x, (axis_name,), to="varying")
            for x in (acc, row_max, row_sum)
        )

    def ring_step(step, carry):
        acc, row_max, row_sum, k_blk, v_blk, k_pos = carry

        # Causal skip: a KV block whose earliest position is beyond this
        # shard's last query position is fully masked — skip its matmuls
        # while still rotating it along the ring. With the contiguous layout
        # this halves attention FLOPs (energy), but per-step latency is set
        # by the slowest device since ppermute is a barrier; a load-balanced
        # (zigzag/striped) sequence layout would convert the saving into
        # wall-clock time and is the natural next step.
        block_relevant = jnp.min(k_pos) <= jnp.max(q_positions)
        acc, row_max, row_sum = jax.lax.cond(
            block_relevant,
            lambda ops: _block_attention(
                qg, ops[0], ops[1], q_positions, ops[2], scale, *ops[3:]
            ),
            lambda ops: (ops[3], ops[4], ops[5]),
            (k_blk, v_blk, k_pos, acc, row_max, row_sum),
        )
        # Rotate KV to the next ring position (keeping the final, unused hop
        # is fine: the loop is static and XLA overlaps it).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        k_pos = jax.lax.ppermute(k_pos, axis_name, perm)
        return acc, row_max, row_sum, k_blk, v_blk, k_pos

    carry = (acc, row_max, row_sum, k, v, k_positions)
    carry = jax.lax.fori_loop(0, axis_size, ring_step, carry)
    acc, row_max, row_sum = carry[:3]

    # Fully-masked rows (possible with user-supplied positions, e.g. packed
    # padding) must yield 0: their row_max never left _NEG_INF, and the
    # softmax shift would otherwise turn the all-masked scores into uniform
    # weights (mean of V).
    masked = row_max <= _NEG_INF
    out = jnp.where(
        masked[..., None], 0.0, acc / jnp.maximum(row_sum[..., None], 1e-30)
    )
    return out.reshape(b, s_local, h, d).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: jax.sharding.Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Convenience wrapper: shard_map ring_attention over ``mesh`` with the
    sequence dim split on ``axis_name`` (other dims replicated)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)

    def inner(q_, k_, v_):
        return ring_attention(q_, k_, v_, axis_name=axis_name, scale=scale)

    return shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
