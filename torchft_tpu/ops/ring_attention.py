"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context is first-class in this framework even though the reference has
no context-parallel code (SURVEY.md §2.7: absent; the FT replica axis stays
orthogonal so a CP/ring axis fits inside the slice). Design follows the
blockwise/ring attention literature (Liu et al., https://arxiv.org/abs/2310.01889):

Each device in the ``sp`` axis holds one sequence shard of Q, K, V. K/V
blocks rotate around the ring via ``jax.lax.ppermute`` while every device
accumulates attention for its local Q block with an **online softmax**
(running max + normalizer, flash-attention style), so the full sequence
never materializes on one chip. Causality is enforced per ring step by
comparing global position ids — a shard attends to a rotated KV block only
where q_pos >= k_pos, which also makes the code correct for any sequence
layout (contiguous shards being the standard one).

Use inside shard_map/jit over a mesh with the ``sp`` axis, activations
sharded (batch, seq/sp, heads, head_dim). Compute rides the MXU per block;
ICI traffic is one KV block per step, overlapped by XLA with the block
matmuls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "blockwise_attention",
    "ring_attention",
    "ring_attention_flash",
    "ring_attention_sharded",
    "ring_attention_zigzag",
    "zigzag_permutation",
]

_NEG_INF = -1e30


def _block_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    k_pos: jnp.ndarray,
    scale: float,
    acc: jnp.ndarray,
    row_max: jnp.ndarray,
    row_sum: jnp.ndarray,
):
    """One flash-style block update.

    q: (b, sq, kv, g, d); k/v: (b, sk, kv, d); positions (b, sq)/(b, sk).
    acc: (b, sq, kv, g, d) f32; row_max/row_sum: (b, sq, kv, g) f32.
    """
    scores = jnp.einsum("bskgd,btkd->bskgt", q, k).astype(jnp.float32) * scale
    causal = q_pos[:, :, None, None, None] >= k_pos[:, None, None, None, :]
    scores = jnp.where(causal, scores, _NEG_INF)

    block_max = jnp.max(scores, axis=-1)
    new_max = jnp.maximum(row_max, block_max)
    # Rescale the old accumulator to the new max.
    correction = jnp.exp(row_max - new_max)
    probs = jnp.exp(scores - new_max[..., None])
    new_sum = row_sum * correction + jnp.sum(probs, axis=-1)
    block_out = jnp.einsum("bskgt,btkd->bskgd", probs.astype(v.dtype), v).astype(
        jnp.float32
    )
    new_acc = acc * correction[..., None] + block_out
    return new_acc, new_max, new_sum


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    block_size: int = 512,
) -> jnp.ndarray:
    """Memory-bounded causal GQA attention on ONE device.

    The single-device sibling of :func:`ring_attention`: a ``lax.scan`` over
    KV blocks with the same online-softmax block update, so activation
    memory is O(s·block) instead of dense attention's O(s²) — in BOTH
    directions: a flash-style ``custom_vjp`` saves only (q, k, v, out,
    logsumexp) and recomputes each block's probabilities in the backward
    pass (a plain scan would stack per-block residuals and give the
    quadratic memory right back under AD). Static shapes, no
    data-dependent control flow; each block's matmuls ride the MXU.

    Shapes: q (b, s, h, d); k/v (b, s, kv_heads, d). The sequence is padded
    to a multiple of ``block_size``; padded KV positions are masked out by
    the causal position comparison (their positions sit beyond every real
    query).
    """
    b, s, h, d = q.shape
    if scale is None:
        scale = d**-0.5
    return _blockwise_core(q, k, v, float(scale), int(block_size))


def _blockwise_blocks(k: jnp.ndarray, v: jnp.ndarray, block_size: int):
    """Pads K/V to a block multiple and returns (k_blocks, v_blocks,
    k_pos_blocks) with the block axis leading (scan xs layout)."""
    b, s = k.shape[0], k.shape[1]
    pad = (-s) % block_size
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = (s + pad) // block_size
    kv_heads, d = k.shape[2], k.shape[3]
    k_blocks = k.reshape(b, n_blocks, block_size, kv_heads, d).swapaxes(0, 1)
    v_blocks = v.reshape(b, n_blocks, block_size, kv_heads, d).swapaxes(0, 1)
    kp = jnp.broadcast_to(jnp.arange(s + pad), (b, s + pad))
    kp_blocks = kp.reshape(b, n_blocks, block_size).swapaxes(0, 1)
    return k_blocks, v_blocks, kp_blocks, n_blocks, pad


def _blockwise_fwd_impl(q, k, v, scale: float, block_size: int):
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    q_pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    k_blocks, v_blocks, kp_blocks, _, _ = _blockwise_blocks(k, v, block_size)

    qg = q.reshape(b, s, kv_heads, group, d)
    acc = jnp.zeros((b, s, kv_heads, group, d), dtype=jnp.float32)
    row_max = jnp.full((b, s, kv_heads, group), _NEG_INF, dtype=jnp.float32)
    row_sum = jnp.zeros((b, s, kv_heads, group), dtype=jnp.float32)

    def scan_step(carry, blk):
        acc, row_max, row_sum = carry
        k_blk, v_blk, kp_blk = blk
        acc, row_max, row_sum = _block_attention(
            qg, k_blk, v_blk, q_pos, kp_blk, scale, acc, row_max, row_sum
        )
        return (acc, row_max, row_sum), None

    (acc, row_max, row_sum), _ = jax.lax.scan(
        scan_step, (acc, row_max, row_sum), (k_blocks, v_blocks, kp_blocks)
    )
    safe_sum = jnp.maximum(row_sum, 1e-30)
    out = (acc / safe_sum[..., None]).reshape(b, s, h, d).astype(q.dtype)
    lse = row_max + jnp.log(safe_sum)  # (b, s, kv, g) f32
    return out, lse


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _blockwise_core(q, k, v, scale: float, block_size: int):
    return _blockwise_fwd_impl(q, k, v, scale, block_size)[0]


def _blockwise_core_fwd(q, k, v, scale: float, block_size: int):
    out, lse = _blockwise_fwd_impl(q, k, v, scale, block_size)
    return out, (q, k, v, out, lse)


def _blockwise_core_bwd(scale: float, block_size: int, residuals, d_out):
    q, k, v, out, lse = residuals
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    q_pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    k_blocks, v_blocks, kp_blocks, n_blocks, pad = _blockwise_blocks(
        k, v, block_size
    )

    qg = q.reshape(b, s, kv_heads, group, d).astype(jnp.float32)
    og = out.reshape(b, s, kv_heads, group, d).astype(jnp.float32)
    dog = d_out.reshape(b, s, kv_heads, group, d).astype(jnp.float32)
    # delta_i = sum_d dO_i . O_i  (flash-attention-2 backward identity).
    delta = jnp.sum(dog * og, axis=-1)  # (b, s, kv, g)

    def scan_step(dq_acc, blk):
        k_blk, v_blk, kp_blk = blk
        k32 = k_blk.astype(jnp.float32)
        v32 = v_blk.astype(jnp.float32)
        scores = jnp.einsum("bskgd,btkd->bskgt", qg, k32) * scale
        causal = q_pos[:, :, None, None, None] >= kp_blk[:, None, None, None, :]
        # p rebuilt from the saved logsumexp; masked entries exactly 0.
        p = jnp.where(causal, jnp.exp(scores - lse[..., None]), 0.0)
        dv_blk = jnp.einsum("bskgt,bskgd->btkd", p, dog)
        dp = jnp.einsum("bskgd,btkd->bskgt", dog, v32)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bskgt,btkd->bskgd", ds, k32)
        dk_blk = jnp.einsum("bskgt,bskgd->btkd", ds, qg)
        return dq_acc, (dk_blk, dv_blk)

    dq_init = jnp.zeros((b, s, kv_heads, group, d), dtype=jnp.float32)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        scan_step, dq_init, (k_blocks, v_blocks, kp_blocks)
    )
    dk = dk_blocks.swapaxes(0, 1).reshape(b, n_blocks * block_size, kv_heads, d)
    dv = dv_blocks.swapaxes(0, 1).reshape(b, n_blocks * block_size, kv_heads, d)
    if pad:
        dk = dk[:, :s]
        dv = dv[:, :s]
    return (
        dq.reshape(b, s, h, d).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


_blockwise_core.defvjp(_blockwise_core_fwd, _blockwise_core_bwd)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    scale: Optional[float] = None,
    q_positions: Optional[jnp.ndarray] = None,
    k_positions: Optional[jnp.ndarray] = None,
    kv_sub_blocks: int = 1,
) -> jnp.ndarray:
    """Causal GQA attention with K/V rotating over ``axis_name``.

    Call from inside shard_map (or jit-with-sharding) where the seq dim of
    q/k/v is the per-device shard. Shapes: q (b, s_local, h, d);
    k/v (b, s_local, kv_heads, d). Positions default to contiguous shards
    ordered by the device's axis index.

    ``kv_sub_blocks``: each rotated KV block is processed in this many
    sequence sub-blocks, each causally skipped independently — with the
    zigzag layout (2 chunks per shard) this is what turns the skip into a
    balanced wall-clock saving.
    """
    axis_size = jax.lax.psum(1, axis_name)
    axis_index = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    if scale is None:
        scale = d**-0.5

    if q_positions is None:
        base = axis_index * s_local
        q_positions = jnp.broadcast_to(base + jnp.arange(s_local), (b, s_local))
    if k_positions is None:
        k_positions = q_positions

    qg = q.reshape(b, s_local, kv_heads, group, d)
    acc = jnp.zeros((b, s_local, kv_heads, group, d), dtype=jnp.float32)
    row_max = jnp.full((b, s_local, kv_heads, group), _NEG_INF, dtype=jnp.float32)
    row_sum = jnp.zeros((b, s_local, kv_heads, group), dtype=jnp.float32)
    # The constant-initialized carries must be marked varying over the ring
    # axis or the fori_loop carry types mismatch under shard_map's
    # varying-manual-axes checking.
    if hasattr(jax.lax, "pcast"):
        acc, row_max, row_sum = (
            jax.lax.pcast(x, (axis_name,), to="varying")
            for x in (acc, row_max, row_sum)
        )

    if s_local % kv_sub_blocks != 0:
        raise ValueError(
            f"kv_sub_blocks ({kv_sub_blocks}) must divide the shard ({s_local})"
        )
    sub = s_local // kv_sub_blocks

    def ring_step(step, carry):
        acc, row_max, row_sum, k_blk, v_blk, k_pos = carry

        # Causal skip, per (query sub-block, KV sub-block) pair: a pair
        # whose earliest KV position exceeds the sub-block's last query
        # position is fully masked — skip its matmuls while the block still
        # rotates. With the contiguous layout (kv_sub_blocks=1) this halves
        # attention FLOPs but latency stays bound by the busiest device
        # (ppermute is a barrier); the zigzag layout + sub_blocks=2 makes
        # every device's relevant-pair count equal, so the saving shows up
        # in wall-clock time.
        if kv_sub_blocks == 1:
            # Direct path: one causal-skip decision for the whole block
            # (avoids the sliced-accumulator machinery entirely).
            relevant = jnp.min(k_pos) <= jnp.max(q_positions)
            acc, row_max, row_sum = jax.lax.cond(
                relevant,
                lambda ops: _block_attention(
                    qg, ops[0], ops[1], q_positions, ops[2], scale, *ops[3:]
                ),
                lambda ops: (ops[3], ops[4], ops[5]),
                (k_blk, v_blk, k_pos, acc, row_max, row_sum),
            )
            return acc, row_max, row_sum, *_rotate(k_blk, v_blk, k_pos)
        for qi in range(kv_sub_blocks):
            q_sub = qg[:, qi * sub : (qi + 1) * sub]
            qp_sub = q_positions[:, qi * sub : (qi + 1) * sub]
            acc_sub = acc[:, qi * sub : (qi + 1) * sub]
            rm_sub = row_max[:, qi * sub : (qi + 1) * sub]
            rs_sub = row_sum[:, qi * sub : (qi + 1) * sub]
            q_sub_max = jnp.max(qp_sub)
            for ki in range(kv_sub_blocks):
                k_sub = k_blk[:, ki * sub : (ki + 1) * sub]
                v_sub = v_blk[:, ki * sub : (ki + 1) * sub]
                p_sub = k_pos[:, ki * sub : (ki + 1) * sub]
                relevant = jnp.min(p_sub) <= q_sub_max
                acc_sub, rm_sub, rs_sub = jax.lax.cond(
                    relevant,
                    lambda ops: _block_attention(
                        q_sub, ops[0], ops[1], qp_sub, ops[2], scale, *ops[3:]
                    ),
                    lambda ops: (ops[3], ops[4], ops[5]),
                    (k_sub, v_sub, p_sub, acc_sub, rm_sub, rs_sub),
                )
            # dynamic_update_slice (not .at[].set): scatter transposes break
            # shard_map AD's sharding inference here.
            acc = jax.lax.dynamic_update_slice_in_dim(acc, acc_sub, qi * sub, axis=1)
            row_max = jax.lax.dynamic_update_slice_in_dim(row_max, rm_sub, qi * sub, axis=1)
            row_sum = jax.lax.dynamic_update_slice_in_dim(row_sum, rs_sub, qi * sub, axis=1)
        return acc, row_max, row_sum, *_rotate(k_blk, v_blk, k_pos)

    def _rotate(k_blk, v_blk, k_pos):
        # Rotate KV to the next ring position (keeping the final, unused hop
        # is fine: the loop is static and XLA overlaps it).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        return (
            jax.lax.ppermute(k_blk, axis_name, perm),
            jax.lax.ppermute(v_blk, axis_name, perm),
            jax.lax.ppermute(k_pos, axis_name, perm),
        )

    carry = (acc, row_max, row_sum, k, v, k_positions)
    carry = jax.lax.fori_loop(0, axis_size, ring_step, carry)
    acc, row_max, row_sum = carry[:3]

    # Fully-masked rows (possible with user-supplied positions, e.g. packed
    # padding) must yield 0: their row_max never left _NEG_INF, and the
    # softmax shift would otherwise turn the all-masked scores into uniform
    # weights (mean of V).
    masked = row_max <= _NEG_INF
    out = jnp.where(
        masked[..., None], 0.0, acc / jnp.maximum(row_sum[..., None], 1e-30)
    )
    return out.reshape(b, s_local, h, d).astype(q.dtype)


def _ring_flash_fwd_impl(
    q, k, v, q_pos, k_pos, axis_name, scale, block_q, block_k, interpret
):
    from torchft_tpu.ops.flash_attention import (
        flash_attention_partial,
        merge_attention_partials,
    )

    axis_size = jax.lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    out = jnp.zeros((b, s_local, h, d), jnp.float32)
    lse = jnp.full((b, s_local, h), _NEG_INF, jnp.float32)
    # Constant-initialized carries must be varying over the ring axis (see
    # ring_attention above).
    if hasattr(jax.lax, "pcast"):
        out, lse = (
            jax.lax.pcast(x, (axis_name,), to="varying") for x in (out, lse)
        )

    def ring_step(_, carry):
        out, lse, k_blk, v_blk, kp = carry
        # The fused kernel computes this hop's partial (normalized out +
        # logsumexp); fully-masked hops come back as (0, sentinel) and the
        # merge weights them out exactly. Block-granular causal skipping
        # happens inside the kernel from the position arrays, so zigzag
        # layouts balance without the sliced-accumulator machinery.
        o_p, l_p = flash_attention_partial(
            q, k_blk, v_blk, q_pos, kp,
            scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
        )
        out, lse = merge_attention_partials(
            out, lse, o_p.astype(jnp.float32), l_p
        )
        perm = [(r, (r + 1) % axis_size) for r in range(axis_size)]
        return (
            out,
            lse,
            jax.lax.ppermute(k_blk, axis_name, perm),
            jax.lax.ppermute(v_blk, axis_name, perm),
            jax.lax.ppermute(kp, axis_name, perm),
        )

    out, lse, *_ = jax.lax.fori_loop(
        0, axis_size, ring_step, (out, lse, k, v, k_pos)
    )
    return out.astype(q.dtype), lse


@_partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _ring_flash(
    q, k, v, q_pos, k_pos, axis_name, scale, block_q, block_k, interpret,
    pallas_bwd,
):
    return _ring_flash_fwd_impl(
        q, k, v, q_pos, k_pos, axis_name, scale, block_q, block_k, interpret
    )[0]


def _ring_flash_fwd(
    q, k, v, q_pos, k_pos, axis_name, scale, block_q, block_k, interpret,
    pallas_bwd,
):
    out, lse = _ring_flash_fwd_impl(
        q, k, v, q_pos, k_pos, axis_name, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, q_pos, k_pos, out, lse)


def _ring_bwd_loop(axis_name, dq0, k, v, k_pos, per_hop):
    """The ring-backward scaffold shared by both per-hop engines: f32
    (dq, dk, dv) carries marked varying over the ring axis (fresh zeros —
    a zeros_like of the already-varying inputs would make the pcast a
    rejected varying→varying cast), with each KV block's (dk, dv) partial
    sums riding the rotation home. ``per_hop(k_blk, v_blk, kp)`` returns
    this hop's (dq_inc, dk_inc, dv_inc) in f32.
    (Ring cost: fwd rotates {k, v, pos}; bwd rotates {k, v, pos, dk, dv}.)
    """
    axis_size = jax.lax.psum(1, axis_name)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    if hasattr(jax.lax, "pcast"):
        dq0, dk0, dv0 = (
            jax.lax.pcast(x, (axis_name,), to="varying") for x in (dq0, dk0, dv0)
        )

    def ring_step(_, carry):
        dq, k_blk, v_blk, kp, dk_blk, dv_blk = carry
        dq_inc, dk_inc, dv_inc = per_hop(k_blk, v_blk, kp)
        perm = [(r, (r + 1) % axis_size) for r in range(axis_size)]
        rotate = lambda x: jax.lax.ppermute(x, axis_name, perm)
        return (
            dq + dq_inc,
            rotate(k_blk),
            rotate(v_blk),
            rotate(kp),
            rotate(dk_blk + dk_inc),
            rotate(dv_blk + dv_inc),
        )

    dq, _, _, _, dk, dv = jax.lax.fori_loop(
        0, axis_size, ring_step, (dq0, k, v, k_pos, dk0, dv0)
    )
    return dq, dk, dv


def _ring_flash_bwd_pallas(
    axis_name, scale, block_q, block_k, interpret, residuals, d_out
):
    """Ring backward with the fused Pallas dq/dkv kernels as the per-hop
    block compute: each hop runs flash_attention_partial_bwd with the
    GLOBAL logsumexp (and the hop-invariant delta = rowsum(dO·O), computed
    once). The kernels' position-driven causal block skip gives zigzag
    layouts their balance on the backward too."""
    from torchft_tpu.ops.flash_attention import flash_attention_partial_bwd

    q, k, v, q_pos, k_pos, out, lse = residuals
    b, s_local, h, d = q.shape

    delta = jnp.sum(
        d_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (b, s, h), hop-invariant

    def per_hop(k_blk, v_blk, kp):
        return flash_attention_partial_bwd(
            q, k_blk, v_blk, d_out, out, lse, q_pos, kp,
            scale, block_q, block_k, interpret, delta=delta,
        )

    dq0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    dq, dk, dv = _ring_bwd_loop(axis_name, dq0, k, v, k_pos, per_hop)
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        None,
        None,
    )


def _ring_flash_bwd(
    axis_name, scale, block_q, block_k, interpret, pallas_bwd, residuals, d_out
):
    if pallas_bwd:
        return _ring_flash_bwd_pallas(
            axis_name, scale, block_q, block_k, interpret, residuals, d_out
        )
    return _ring_flash_bwd_scan(
        axis_name, scale, block_q, block_k, interpret, residuals, d_out
    )


def _ring_flash_bwd_scan(axis_name, scale, block_q, block_k, interpret, residuals, d_out):
    """True ring backward from the saved (out, lse) residuals — the
    flash-attention-2 identity with the GLOBAL logsumexp as XLA einsums,
    so no forward recompute is needed. The interpret/CPU engine; shares
    the rotation scaffold with the Pallas engine via _ring_bwd_loop."""
    q, k, v, q_pos, k_pos, out, lse = residuals
    b, s_local, h, d = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads

    qg = q.reshape(b, s_local, kv_heads, group, d).astype(jnp.float32)
    og = out.reshape(b, s_local, kv_heads, group, d).astype(jnp.float32)
    dog = d_out.reshape(b, s_local, kv_heads, group, d).astype(jnp.float32)
    lse_g = lse.reshape(b, s_local, kv_heads, group)
    # delta_i = dO_i . O_i (flash-attention-2 backward identity).
    delta = jnp.sum(dog * og, axis=-1)  # (b, s, kv, g)

    def per_hop(k_blk, v_blk, kp):
        k32 = k_blk.astype(jnp.float32)
        v32 = v_blk.astype(jnp.float32)
        scores = jnp.einsum("bskgd,btkd->bskgt", qg, k32) * scale
        mask = q_pos[:, :, None, None, None] >= kp[:, None, None, None, :]
        # p rebuilt from the merged global logsumexp; masked entries are
        # exactly 0 (fully-masked rows have the -1e30 sentinel, whose exp
        # overflow is discarded by the where).
        p = jnp.where(mask, jnp.exp(scores - lse_g[..., None]), 0.0)
        dv_inc = jnp.einsum("bskgt,bskgd->btkd", p, dog)
        dp = jnp.einsum("bskgd,btkd->bskgt", dog, v32)
        ds = p * (dp - delta[..., None]) * scale
        dq_inc = jnp.einsum("bskgt,btkd->bskgd", ds, k32)
        dk_inc = jnp.einsum("bskgt,bskgd->btkd", ds, qg)
        return dq_inc, dk_inc, dv_inc

    dq0 = jnp.zeros((b, s_local, kv_heads, group, d), jnp.float32)
    dq, dk, dv = _ring_bwd_loop(axis_name, dq0, k, v, k_pos, per_hop)
    return (
        dq.reshape(b, s_local, h, d).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        None,
        None,
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention_flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    scale: Optional[float] = None,
    q_positions: Optional[jnp.ndarray] = None,
    k_positions: Optional[jnp.ndarray] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    use_pallas_bwd: Optional[bool] = None,
) -> jnp.ndarray:
    """:func:`ring_attention` with the fused Pallas kernel as the per-hop
    block compute (ops/flash_attention.py): K/V still rotate over
    ``axis_name`` via ppermute, but each hop's online-softmax inner loop
    runs as one kernel with VMEM-resident accumulators, and hops merge by
    logsumexp. Default blocks follow the flash kernel's on-chip sweep
    (512x1024, see flash_attention's docstring); the kernel entry points
    clamp them to each hop's padded local lengths, so small shards are
    unaffected. Same shapes/semantics as :func:`ring_attention`. The
    backward is a true ring backward from the saved (out, lse); on TPU
    (``use_pallas_bwd=None`` → when the forward compiles) each hop runs
    the fused dq/dkv kernels (flash_attention_partial_bwd), with the
    einsum ring backward as the interpret/CPU fallback."""
    axis_index = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if scale is None:
        scale = d**-0.5
    if q_positions is None:
        base = axis_index * s_local
        q_positions = jnp.broadcast_to(base + jnp.arange(s_local), (b, s_local))
    if k_positions is None:
        k_positions = q_positions
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if use_pallas_bwd is None:
        use_pallas_bwd = not interpret
    return _ring_flash(
        q, k, v,
        q_positions.astype(jnp.int32), k_positions.astype(jnp.int32),
        axis_name, float(scale), int(block_q), int(block_k), bool(interpret),
        bool(use_pallas_bwd),
    )


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: jax.sharding.Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Convenience wrapper: shard_map ring_attention over ``mesh`` with the
    sequence dim split on ``axis_name`` (other dims replicated).
    ``use_flash`` selects the fused Pallas per-hop kernel."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    ring = ring_attention_flash if use_flash else ring_attention

    def inner(q_, k_, v_):
        return ring(q_, k_, v_, axis_name=axis_name, scale=scale)

    return shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def zigzag_permutation(seq_len: int, sp: int):
    """Load-balanced ("zigzag") sequence layout for causal ring attention.

    With contiguous shards, causal skipping idles early-ring devices while
    late ones do full work each step (latency = busiest device). Splitting
    the sequence into ``2*sp`` chunks and giving device ``i`` chunks
    ``(i, 2*sp-1-i)`` equalizes the causally-relevant work per device, so
    the FLOP saving becomes wall-clock saving.

    Returns (perm, inv_perm): apply ``x[:, perm]`` before sharding over
    ``sp`` and pass the matching positions (``perm`` itself) to
    :func:`ring_attention`; apply ``out[:, inv_perm]`` to restore order.
    """
    import numpy as np

    if seq_len % (2 * sp) != 0:
        raise ValueError(f"seq_len {seq_len} must divide by 2*sp ({2 * sp})")
    chunk = seq_len // (2 * sp)
    order = []
    for device in range(sp):
        order.extend([device, 2 * sp - 1 - device])
    perm = np.concatenate(
        [np.arange(c * chunk, (c + 1) * chunk) for c in order]
    )
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return perm, inv


def ring_attention_zigzag(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: jax.sharding.Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Ring attention with the zigzag layout applied transparently: inputs
    and outputs are in natural sequence order; internally the sequence is
    permuted so every ring step does balanced causal work. ``use_flash``
    selects the fused Pallas per-hop kernel, whose in-kernel block-granular
    causal skip replaces the scan path's kv_sub_blocks slicing."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    sp = mesh.shape[axis_name]
    b, s = q.shape[0], q.shape[1]
    perm, inv = zigzag_permutation(s, sp)
    perm_j = jnp.asarray(perm)
    positions = jnp.broadcast_to(perm_j, (b, s))

    spec = P(None, axis_name, None, None)
    pos_spec = P(None, axis_name)

    def inner(q_, k_, v_, pos):
        if use_flash:
            return ring_attention_flash(
                q_, k_, v_, axis_name=axis_name, scale=scale,
                q_positions=pos, k_positions=pos,
            )
        return ring_attention(
            q_, k_, v_, axis_name=axis_name, scale=scale,
            q_positions=pos, k_positions=pos, kv_sub_blocks=2,
        )

    mapped = shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec, pos_spec), out_specs=spec
    )
    out = mapped(q[:, perm_j], k[:, perm_j], v[:, perm_j], positions)
    return out[:, jnp.asarray(inv)]
