"""Fault-tolerant optimizer wrapper (optax).

The canonical step protocol of the reference's ``OptimizerWrapper``
(/root/reference/torchft/optim.py:24-63) — ``zero_grad()`` starts the quorum,
``step()`` commits — adapted for JAX/optax:

    opt = Optimizer(manager, optax.adamw(3e-4), params)
    for batch in data:
        opt.begin_step()                        # zero_grad() analogue
        grads = grad_fn(opt.params, batch)
        avg = manager.allreduce_pytree(grads).wait()
        committed = opt.step(avg)
        # opt.params / opt.opt_state hold the live state

The wrapper *owns* ``params``/``opt_state`` and registers them with the
manager under the key ``"optimizer"`` — this is load-bearing for healing:
``should_commit()`` may replace the state with a donor's checkpoint
mid-call, and the gradient update must apply to the *healed* state, exactly
as torch's in-place ``load_state_dict`` + ``optimizer.step()`` sequence
does. A functional step that captured params before the commit barrier
would silently clobber the heal (the bug class this design avoids).

For custom state management, call ``manager.should_commit()`` directly and
re-read any registered state *after* it returns.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from torchft_tpu import health, metrics, tracing
from torchft_tpu.manager import Manager
from torchft_tpu.utils import schedules
from torchft_tpu.utils.profiling import trace_span

logger = logging.getLogger(__name__)

__all__ = [
    "Optimizer",
    "OptimizerWrapper",
    "make_jit_update",
    "make_jit_shard_update",
    "make_jit_fused_step",
    "make_microbatch_grad",
]


def _bound_device(x: Any) -> Any:
    """Readiness seam for every commit-ordering device sync.

    One named chokepoint instead of inline ``jax.block_until_ready`` calls
    so (a) the ordering tests can spy the sync relative to the vote for all
    three commit orderings, and (b) the emulated-DCN bench can shim it with
    ``netem.emulated_device_sync`` to model the remote-device readiness
    round trip this machine's tunnel charges (~73 ms — the cost the
    pipelined mode exists to hide)."""
    return jax.block_until_ready(x)


def _replica_labels(manager: Any) -> dict:
    """The manager's stable replica labels for optimizer-side counters
    (rollbacks, phantom commits), so drills can count them per replica
    group; {} for scripted/mocked managers without the attribute."""
    return getattr(manager, "_metric_labels", None) or {}


def _trace_of(manager: Any) -> "tracing.TraceJournal":
    """The manager's trace journal (so optimizer events land in the same
    per-replica timeline its manager records into), falling back to the
    thread's current journal for scripted/mocked managers."""
    return getattr(manager, "_trace", None) or tracing.current()


def _sync_device(x: Any) -> Any:
    """Every step's device sync, timed into ``tpuft_device_sync_seconds``.

    Calls through the module global so spies and the netem shim that rebind
    ``_bound_device`` still intercept the sync — and their emulated/observed
    latency lands in the phase histogram like the real one."""
    start = time.perf_counter()
    try:
        with tracing.span("device_sync"):
            # Gray-failure chaos seam: a punisher-armed slow_replica/
            # wedge_device installs a persistent per-replica stall/wedge
            # here (one env lookup when unarmed) — the injected latency
            # lands in the phase histogram and the health scorer's EWMA
            # exactly like a real gray device.
            health.injected_stall("device_sync")
            return _bound_device(x)
    finally:
        metrics.observe("tpuft_device_sync_seconds", time.perf_counter() - start)


def make_microbatch_grad(loss_fn: Any, num_microbatches: int):
    """Gradient accumulation the TPU way: ``(params, *batch) -> (loss,
    grads)`` that splits each batch array's leading axis into
    ``num_microbatches`` equal chunks and ``lax.scan``s value_and_grad over
    them inside ONE traced program — activations for only one microbatch
    are live at a time (the standard HBM lever when the global batch
    doesn't fit), with f32 accumulators so bf16 models don't lose gradient
    mass across chunks. Equal-sized chunks make mean-of-means exactly the
    full-batch mean for per-example/token-mean losses (up to f32 reduction
    order). Every ``*batch`` arg must carry the batch axis at dim 0; pass
    non-batched aux (rng keys, constants) via closure.

    The reference leans on torch's eager semantics for this —
    ``loss.backward()`` accumulates into ``.grad`` buffers between
    ``zero_grad()`` and ``step()`` (the train-loop protocol at
    /root/reference/train_ddp.py:185-196), so users accumulate by simply
    calling backward N times. Under XLA the scan is the idiomatic
    equivalent — no data-dependent Python control flow, one compiled loop
    body reused across chunks."""
    import jax.numpy as jnp

    if num_microbatches < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")

    def grad_fn(params: Any, *batch: Any):
        def split(x):
            # Every *batch leaf must carry the batch axis at dim 0 —
            # pass non-batched aux (rng keys, scalars) via closure, not
            # as a batch arg.
            if getattr(x, "ndim", 0) == 0:
                raise ValueError(
                    "make_microbatch_grad: got a rank-0 batch arg; every "
                    "batch array must have the batch axis at dim 0 (close "
                    "over non-batched aux instead)"
                )
            if x.shape[0] % num_microbatches:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"num_microbatches={num_microbatches}"
                )
            return x.reshape(
                (num_microbatches, x.shape[0] // num_microbatches) + x.shape[1:]
            )

        micro = jax.tree_util.tree_map(split, batch)
        vg = jax.value_and_grad(loss_fn)

        def body(carry, mb):
            loss_acc, g_acc = carry
            loss, g = vg(params, *mb)
            g_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), g_acc, g
            )
            return (loss_acc + loss.astype(jnp.float32), g_acc), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, g_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro
        )
        inv = 1.0 / num_microbatches
        grads = jax.tree_util.tree_map(
            lambda g, p: (g * inv).astype(p.dtype), g_sum, params
        )
        return loss_sum * inv, grads

    return grad_fn


def make_jit_fused_step(tx: Any, loss_fn: Any, num_microbatches: int = 1):
    """ONE jitted program for a whole local train step:
    ``(params, opt_state, *batch) -> (loss, new_params, new_opt_state)``.
    ``loss_fn(params, *batch) -> scalar``. The fused form is the plain-JAX
    train step; Optimizer (lone-replica path) and LocalSGD (inner steps)
    share it — DiLoCo keeps its own leaves-layout variant
    (local_sgd.py make_step_fn). ``num_microbatches > 1`` accumulates
    gradients over equal batch chunks inside the same program
    (:func:`make_microbatch_grad`)."""
    import optax

    if num_microbatches < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")
    if num_microbatches > 1:
        grad_fn = make_microbatch_grad(loss_fn, num_microbatches)
    else:
        grad_fn = jax.value_and_grad(loss_fn)

    def _fused(params: Any, opt_state: Any, *batch: Any):
        loss, grads = grad_fn(params, *batch)
        updates, new_state = tx.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), new_state

    return jax.jit(_fused)


def make_jit_update(tx: Any):
    """One fused-dispatch optax update: (grads, opt_state, params) ->
    (new_params, new_opt_state). Shared by Optimizer/LocalSGD/DiLoCo —
    unjitted optax updates issue hundreds of tiny device ops, which dominates
    on high-latency device links."""
    import optax

    def _update(grads: Any, opt_state: Any, params: Any):
        updates, new_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    return jax.jit(_update)


def make_jit_shard_update(tx: Any):
    """One fused-dispatch optax update over a LIST of optimizer shards:
    ``(avg_shards, shard_states, master_shards) -> (new_masters,
    new_states)`` where each position is one ZeRO shard's flat f32 range
    (torchft_tpu.zero). Each shard keeps its OWN optax state (``tx.init``
    per shard — shard states must stay independently addressable for the
    re-balance exchange and the shard-wise heal), but all owned shards
    update inside ONE jitted program, so the per-step dispatch count stays
    constant regardless of how many shards a replica owns (the
    unjitted-optax invariant: eager per-shard updates would issue hundreds
    of tiny device ops on high-latency links)."""
    import optax

    def _update(avg_shards: Any, shard_states: Any, master_shards: Any):
        new_masters, new_states = [], []
        for grad, state, master in zip(avg_shards, shard_states, master_shards):
            updates, next_state = tx.update(grad, state, master)
            new_masters.append(optax.apply_updates(master, updates))
            new_states.append(next_state)
        return new_masters, new_states

    return jax.jit(_update)


def _align_opt_state(opt_state: Any, params: Any) -> Any:
    """Places optimizer-state leaves on the params' device set.

    Param-shaped leaves (moments) already inherit the params' sharding via
    zeros_like; scalar bookkeeping (e.g. optax's ``count``) lands on one
    local device, which breaks the jitted update under a multi-host mesh —
    replicate those over the params' mesh instead."""
    from jax.sharding import NamedSharding, PartitionSpec

    param_leaves = [
        leaf for leaf in jax.tree_util.tree_leaves(params) if isinstance(leaf, jax.Array)
    ]
    if not param_leaves:
        return opt_state
    sharding = param_leaves[0].sharding
    mesh = getattr(sharding, "mesh", None)
    if mesh is None:
        return opt_state
    target_ids = {d.id for d in param_leaves[0].sharding.device_set}
    if len(target_ids) <= 1:
        return opt_state
    replicated = NamedSharding(mesh, PartitionSpec())

    def fix(leaf: Any) -> Any:
        if isinstance(leaf, jax.Array):
            if {d.id for d in leaf.sharding.device_set} != target_ids:
                return jax.device_put(np.asarray(leaf), replicated)
        return leaf

    return jax.tree_util.tree_map(fix, opt_state)


def _restore_leaf(new: Any, current: Any) -> Any:
    """Restores a healed leaf onto the device layout of ``current``.

    Plain hosts arrays follow the current sharding; a
    :class:`~torchft_tpu.checkpointing._serialization.ShardedLeaf` (multi-
    host donor capture) is reassembled shard-by-shard against the current
    array's sharding — donor and joiner lay out identically by the HSDP
    contract (same model, same intra-group mesh)."""
    import jax.numpy as jnp

    from torchft_tpu.checkpointing._serialization import ShardedLeaf, _resolve_dtype

    if isinstance(new, ShardedLeaf):
        if not isinstance(current, jax.Array):
            raise TypeError(
                "received a sharded checkpoint leaf but the local state is "
                "not a jax.Array to supply its sharding"
            )
        by_index = dict(new.shards)
        buffers = []
        for shard in current.addressable_shards:
            key = ShardedLeaf.index_key(shard.index, new.global_shape)
            if key not in by_index:
                raise ValueError(
                    f"donor checkpoint lacks shard {key}: donor/joiner "
                    "shardings must match"
                )
            buffers.append(
                jax.device_put(
                    np.asarray(by_index[key], dtype=_resolve_dtype(new.dtype)),
                    shard.device,
                )
            )
        return jax.make_array_from_single_device_arrays(
            new.global_shape, current.sharding, buffers
        )
    if isinstance(current, jax.Array) and hasattr(new, "shape"):
        return jax.device_put(np.asarray(new), current.sharding)
    if hasattr(new, "shape"):
        return jnp.asarray(new)
    return new


def _as_device_tree(tree: Any, like: Any = None) -> Any:
    import jax.numpy as jnp

    if like is not None:
        return jax.tree_util.tree_map(
            _restore_leaf, tree, like,
            is_leaf=lambda x: not isinstance(x, (dict, list, tuple)),
        )
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if hasattr(x, "shape") else x, tree
    )


# Rollback-unwind depth is a small count (1..window depth), not seconds:
# its histogram gets count-shaped edges instead of the shared time ladder.
_UNWIND_DEPTH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)


class _PendingStep:
    """One slot of the speculative commit window: the slot's speculative
    ``(params, opt_state)`` is already adopted as the live state (so
    younger steps could dispatch on it), and this record carries
    everything needed to confirm, roll back, or re-derive it once its
    commit verdict lands — plus the window bookkeeping the depth-N
    generalization needs: ``claimed_step`` (the step this slot
    speculates), ``gen`` (the speculation generation at dispatch; a
    rollback bumps the owner's generation, turning every younger
    undrained slot into a discard), and ``snapshot_bytes`` (this slot's
    share of the snapshot ring, for the resident-bytes gauge).

    Both phases are idempotent and lock-guarded because two threads may
    reach them: the train loop (the normal resolution path) and the
    manager's quorum thread (the drain-before-reconfigure hook)."""

    __slots__ = (
        "manager",
        "heal_count",
        "loss",
        "snapshot",
        "recompute",
        "commit_future",
        "committed",
        "gen",
        "claimed_step",
        "discarded",
        "snapshot_bytes",
        "_bound",
        "_bound_error",
        "_lock",
    )

    def __init__(
        self, manager: Manager, heal_count: int, loss: Any, snapshot: Any,
        recompute: Any, commit_future: Any, gen: int = 0,
        claimed_step: int = -1, snapshot_bytes: int = 0,
    ) -> None:
        self.manager = manager
        self.heal_count = heal_count
        self.loss = loss
        self.snapshot = snapshot
        self.recompute = recompute
        self.commit_future = commit_future
        self.committed: Optional[bool] = None  # set by the vote resolution
        self.gen = gen
        self.claimed_step = claimed_step
        self.discarded = False
        self.snapshot_bytes = snapshot_bytes
        self._bound = False
        self._bound_error: Optional[BaseException] = None
        self._lock = threading.Lock()

    def bound_device(self, raise_on_error: bool = True) -> None:
        """Observes this step's device completion (once). A failure here is
        the widened envelope's bounded-accounting case: the step may
        already have committed (vote resolved before completion), so the
        error is logged with that context and funneled into
        :meth:`Manager.report_error` — poisoning the NEXT commit, whose
        resolution rolls the speculative successor back. ``raise_on_error``
        is False on the quorum-thread drain (report, don't unwind the
        quorum) and True on the train-loop path (the supervisor-restart
        boundary owns hard device failures, as in the non-pipelined
        orderings)."""
        with self._lock:
            if not self._bound:
                self._bound = True
                try:
                    _sync_device(self.loss)
                except BaseException as e:  # noqa: BLE001
                    self._bound_error = e
                    if self.committed:
                        metrics.inc(
                            "tpuft_phantom_commits_total",
                            **_replica_labels(self.manager),
                        )
                        _trace_of(self.manager).record(
                            "phantom_commit", error=str(e)
                        )
                    logger.error(
                        "pipelined step's device work failed after its commit "
                        "vote resolved committed=%s (a committed step here "
                        "advanced the step counter without a verified update "
                        "— the bounded phantom-commit envelope, at most "
                        "window-depth steps)",
                        self.committed,
                    )
                    if isinstance(e, Exception):
                        self.manager.report_error(e)
        if self._bound_error is not None and raise_on_error:
            raise self._bound_error


class Optimizer:
    """Owns (params, opt_state); steps only on quorum-wide commit."""

    def __init__(
        self,
        manager: Manager,
        tx: Any,
        params: Any,
        register_key: str = "optimizer",
    ) -> None:
        self.manager = manager
        self.tx = tx
        self.params = params
        self._heal_count = 0
        self._register_key = register_key
        self.opt_state = self._init_state(tx, params)
        manager.register_state_dict_fn(
            register_key, self._load_state_dict, self._state_dict
        )

        self._jit_update = make_jit_update(tx)

        # Pipelined-commit state (populated by make_step_fn when the
        # manager's commit_pipeline_depth >= 1; the depth-N window keeps
        # up to N of these records in flight at once).
        self._pipeline: Optional[Any] = None
        self._pipeline_hooked = False
        self._next_pipelined_step = 0
        self.rollback_count = 0
        # Speculation generation: bumped by a rollback so every younger
        # undrained window slot resolves as a discard (the step never
        # happened, quorum-wide) instead of adopting state computed on a
        # refused speculation.
        self._speculation_gen = 0
        self._snapshot_ring_bytes = 0

    def _init_state(self, tx: Any, params: Any) -> Any:
        """Builds the initial optimizer state this wrapper owns. The ZeRO
        subclass (torchft_tpu.zero.ZeroOptimizer) overrides this to hold
        only its 1/N shard of the state; everything downstream (snapshots,
        rollback, heal re-binding) treats ``opt_state`` as opaque."""
        return _align_opt_state(tx.init(params), params)

    def _state_dict(self) -> Any:
        return {"params": self.params, "opt_state": self.opt_state}

    # tpuft: allow(lock-discipline): heal apply — the registered load fns run under the state-dict writer taken by Manager._apply_pending_state_dict
    def _load_state_dict(self, state: Any) -> None:
        # Restore against the CURRENT layouts so multi-host shardings are
        # reassembled locally (each rank received its own shards).
        self.params = _as_device_tree(state["params"], like=self.params)
        self.opt_state = _as_device_tree(state["opt_state"], like=self.opt_state)
        # Any speculative update dispatched before this heal is stale.
        self._heal_count += 1

    def begin_step(
        self, timeout: Optional[float] = None, shrink_only: bool = False
    ) -> None:
        """Starts the (async) quorum for this step; call before the forward
        pass so quorum latency overlaps compute."""
        self.manager.start_quorum(shrink_only=shrink_only, timeout=timeout)

    # torch-API alias: the reference starts quorum in zero_grad().
    zero_grad = begin_step

    def step(self, grads: Any, timeout: Optional[float] = None) -> bool:
        """Commits the step; on success applies ``grads`` to the (possibly
        just-healed) owned state. Returns whether the step committed.

        The update is dispatched **speculatively** and the commit-barrier
        RPC rides the manager's executor, so BOTH the RPC wire time and the
        device-side optimizer math overlap (the analogue of the reference
        overlapping should_commit's stream syncs, manager.py:569-581 +
        :816-827). If the barrier heals this replica (state replaced
        mid-call), the speculation is discarded and the update re-applies
        against the healed state."""
        # Bound the device work before voting: a replica whose math never
        # finished must not vote to commit (the stream-sync analogue of
        # reference manager.py:816-827).
        grads = _sync_device(grads)
        heal_count = self._heal_count
        # Snapshot the state refs, THEN launch the barrier: the RPC is in
        # flight while the update dispatches below. A concurrent heal can
        # rebind self.params mid-dispatch — harmless, because the
        # heal_count check discards the speculation in that case.
        params, opt_state = self.params, self.opt_state
        commit_future = self.manager.should_commit_async(timeout)
        try:
            with metrics.timer("tpuft_update_dispatch_seconds"), _trace_of(
                self.manager
            ).span("update_dispatch"):
                spec = self._jit_update(grads, opt_state, params)
        except BaseException:
            # The barrier is already in flight and may commit the step
            # (the vote was computed from pre-dispatch health); never leave
            # it dangling on the executor — resolve it, then surface the
            # dispatch failure (the supervisor restart + heal path owns
            # recovery from a step counter that advanced without its
            # update).
            try:
                barrier_result = commit_future.result()
            except Exception:
                # Both causes matter to a supervisor diagnosing "step
                # advanced without its update": keep the barrier's failure
                # (e.g. should_commit's max_retries RuntimeError) visible
                # alongside the dispatch failure we re-raise below.
                logger.exception(
                    "commit barrier also failed while handling an optimizer "
                    "dispatch failure; barrier outcome lost to the re-raise"
                )
            else:
                if barrier_result:
                    metrics.inc(
                        "tpuft_phantom_commits_total",
                        **_replica_labels(self.manager),
                    )
                    _trace_of(self.manager).record("phantom_commit")
                logger.error(
                    "optimizer dispatch failed with the commit barrier in "
                    "flight; barrier resolved committed=%s (a committed step "
                    "here advanced the step counter without its update)",
                    barrier_result,
                )
            raise
        return self._commit_and_adopt(
            heal_count,
            spec,
            lambda: self._jit_update(grads, self.opt_state, self.params),
            timeout,
            commit_future=commit_future,
        )

    def _commit_and_adopt(
        self,
        heal_count: int,
        speculation: Any,
        recompute: Any,
        timeout: Optional[float],
        commit_future: Any = None,
    ) -> bool:
        """The shared barrier protocol: vote/commit, then adopt the
        speculatively computed ``(params, opt_state)`` — unless the barrier
        healed this replica (state replaced mid-call), in which case
        ``recompute()`` re-derives the update against the healed state.

        NOTE: should_commit may invoke _load_state_dict (healing); read
        self.params/opt_state only after it returns. The mutation is
        write-locked so a concurrent checkpoint capture (donor staging on
        the quorum thread) never reads a torn params/opt pair."""
        committed = (
            commit_future.result()
            if commit_future is not None
            else self.manager.should_commit(timeout=timeout)
        )
        if not committed:
            return False
        self.manager.disallow_state_dict_read()
        try:
            if self._heal_count != heal_count:
                self.params, self.opt_state = recompute()
            else:
                self.params, self.opt_state = speculation
        finally:
            self.manager.allow_state_dict_read()
        # Promote the just-committed state into the manager's history
        # ring (refs only — immutable trees make holding a reference a
        # true snapshot). The barrier already advanced the step counter.
        self._promote_committed(
            self._int_or_none(self.manager.current_step()),
            self.params,
            self.opt_state,
        )
        return True

    # ------------------------------------------------------------------
    # versioned weight history (torchft_tpu/history.py)
    # ------------------------------------------------------------------

    @staticmethod
    def _int_or_none(value: Any) -> Optional[int]:
        return value if isinstance(value, int) else None

    def _promote_committed(
        self, step: Optional[int], params: Any, opt_state: Any
    ) -> None:
        """Hands one committed step's ``(params, opt_state)`` refs to the
        manager's history ring — the slot promotion that replaces simply
        dropping resolved window snapshots. Best-effort: history is an
        availability plane (exact deep-window heals, pinned serving);
        its bookkeeping must never wound a commit."""
        if step is None:
            return
        hist = getattr(self.manager, "history", None)
        try:
            from torchft_tpu.history import WeightHistory

            if not isinstance(hist, WeightHistory):
                return  # scripted/mocked managers without a real ring
            state = {"params": params, "opt_state": opt_state}
            hist.note_state(
                self._register_key,
                step,
                state,
                nbytes=self._snapshot_nbytes((params, opt_state)),
                quorum_id=getattr(self.manager, "_quorum_id", None),
            )
        except Exception:  # noqa: BLE001 — bookkeeping must not wound a step
            logger.exception("history promotion failed (ignored)")

    def _post_commit_state(self, rec: "_PendingStep") -> Any:
        """The committed state AFTER ``rec``'s step: the next younger
        same-generation window slot's pre-step snapshot (speculations
        chain — slot k+1's snapshot IS post-k state), or the live state
        when ``rec`` is the window's newest resolved slot."""
        if self._pipeline is not None:
            seen = False
            for r in self._pipeline.pending():
                if r is rec:
                    seen = True
                    continue
                if not seen or r.gen != rec.gen or r.committed is not None:
                    continue
                return r.snapshot
        return (self.params, self.opt_state)

    # ------------------------------------------------------------------
    # pipelined commit (depth N): resolution machinery
    # ------------------------------------------------------------------

    def pending_commits(self) -> int:
        """Uncommitted pipelined steps currently in flight (0 up to the
        window depth)."""
        return len(self._pipeline) if self._pipeline is not None else 0

    def _snapshot_nbytes(self, snapshot: Any) -> int:
        """Approximate resident bytes of one rollback snapshot (device
        array leaves by ``nbytes``; opaque states that expose
        ``owned_bytes`` — the ZeRO shard state — by that). Feeds the
        ``tpuft_pipeline_snapshot_bytes`` gauge: the window holds one
        (params, opt_state) copy per slot, which is THE memory cost of
        deepening it (the doctor's depth probe states the formula)."""
        total = 0
        try:
            for leaf in jax.tree_util.tree_leaves(
                snapshot, is_leaf=lambda x: hasattr(x, "owned_bytes")
            ):
                owned = getattr(leaf, "owned_bytes", None)
                if owned is not None:
                    total += int(owned)
                else:
                    total += int(getattr(leaf, "nbytes", 0) or 0)
        except Exception:  # noqa: BLE001 — a gauge must never wound a step
            return 0
        return total

    def _note_snapshot(self, rec: "_PendingStep", admitted: bool) -> None:
        if admitted:
            self._snapshot_ring_bytes += rec.snapshot_bytes
        else:
            self._snapshot_ring_bytes = max(
                0, self._snapshot_ring_bytes - rec.snapshot_bytes
            )
        metrics.set_gauge(
            "tpuft_pipeline_snapshot_bytes", self._snapshot_ring_bytes
        )

    def next_pipelined_step(self) -> int:
        """The step index the next pipelined ``step_fn`` call will compute.

        ``manager.current_step()`` is unstable while a pipelined vote is in
        flight (it advances on the manager's executor the moment the
        barrier resolves), so DDP loops that key their data stream on the
        step must use this caller-thread-maintained prediction instead. It
        assumes every in-flight step commits; a failed commit or a heal
        makes up to window-depth predictions stale, and the next call
        re-anchors — every replica observes the same quorum-wide verdicts,
        so the streams stay in lockstep."""
        return self._next_pipelined_step

    def _resolve_pipelined_record(self, rec: _PendingStep) -> bool:
        """Vote phase: reads the barrier verdict and reconciles the already
        adopted speculation — confirm (no-op), roll back to the pre-step
        snapshot on a failed commit (discarding every younger slot of the
        window: the refusal is quorum-wide, so all survivors unwind the
        same suffix identically), or (same semantics as
        :meth:`_commit_and_adopt`) re-derive the update against a state the
        barrier healed — younger slots re-derive in turn when they become
        oldest, replaying the whole window's grads onto the healed state.
        Idempotent: the quorum-change drain and the train loop may both
        reach it."""
        schedules.point("optim.resolve_record")
        with rec._lock:
            if rec.committed is not None:
                return rec.committed
            if rec.gen != self._speculation_gen:
                # A rollback unwound the window past this slot: the step
                # never happened (quorum-wide). Consume the in-flight
                # verdict WITHOUT accounting and skip the device bound —
                # the work was discarded along with the state it computed.
                rec.discarded = True
                rec._bound = True
                discard = getattr(rec.commit_future, "discard", None)
                if discard is not None:
                    discard()
                else:  # pragma: no cover — depth-1 windows have no youngers
                    try:
                        rec.commit_future.result()
                    except Exception:  # noqa: BLE001
                        pass
                _trace_of(self.manager).record(
                    "speculation_discarded", step=rec.claimed_step
                )
                rec.committed = False
                return False
            with trace_span(
                "tpuft::optim::resolve_pipelined_commit",
                step=self.manager.current_step(),
            ):
                committed = rec.commit_future.result()
                rolled_back = False
                discarded = 0
                self.manager.disallow_state_dict_read()
                try:
                    if self._heal_count != rec.heal_count:
                        # Healed mid-flight: the donor state is
                        # authoritative; a committed step still owes its
                        # update (pre-heal grads applied to the healed state
                        # — reference load_state_dict + optimizer.step()
                        # order).
                        if committed:
                            self.params, self.opt_state = rec.recompute()
                    elif not committed:
                        # Refuse to adopt: restore the pre-step state the
                        # speculation was dispatched from, and turn every
                        # younger in-flight slot into a discard — their
                        # speculations chain from this refused one.
                        self.params, self.opt_state = rec.snapshot
                        self.rollback_count += 1
                        rolled_back = True
                        pending = (
                            self._pipeline.pending()
                            if self._pipeline is not None
                            else ()
                        )
                        discarded = sum(
                            1
                            for r in pending
                            if r is not rec and r.gen == rec.gen
                        )
                        self._speculation_gen += 1
                        metrics.inc(
                            "tpuft_rollbacks_total",
                            **_replica_labels(self.manager),
                        )
                        metrics.histogram(
                            "tpuft_rollback_unwind_depth",
                            buckets=_UNWIND_DEPTH_BUCKETS,
                        ).observe(1 + discarded)
                finally:
                    self.manager.allow_state_dict_read()
                if committed:
                    # Ring-slot promotion: the resolved slot's committed
                    # state enters the step-labeled history instead of
                    # being dropped — after a heal it is the live
                    # (just-recomputed) state; otherwise the next younger
                    # slot's snapshot (speculations chain).
                    self._promote_committed(
                        rec.claimed_step + 1
                        if rec.claimed_step >= 0
                        else self._int_or_none(self.manager.current_step()),
                        *(
                            (self.params, self.opt_state)
                            if self._heal_count != rec.heal_count
                            else self._post_commit_state(rec)
                        ),
                    )
                if rolled_back:
                    # Incident capture runs OUTSIDE the writer: dumping
                    # journals is file I/O a concurrent checkpoint serve
                    # must not wait on. Quorum-wide refusal means every
                    # survivor rolls this step back identically and derives
                    # the SAME incident id — the fleet's journals + flight
                    # recorders dump under one correlatable stamp.
                    journal = _trace_of(self.manager)
                    rolled_step = self.manager.current_step()
                    rolled_quorum = getattr(self.manager, "_quorum_id", -1)
                    journal.record(
                        "rollback",
                        step=rolled_step,
                        quorum_id=rolled_quorum,
                        unwound_to=rolled_step,
                        discarded=discarded,
                    )
                    tracing.open_incident(
                        "rollback", rolled_step, rolled_quorum,
                        journal=journal,
                        reason="speculative step refused by the commit barrier",
                    )
                    # Serving plane: an unwind retracts any due-but-
                    # unpublished version newer than the surviving
                    # committed step — a discarded speculation must never
                    # surface to readers (published versions are post-
                    # barrier and final, so this is the only window).
                    publisher = getattr(self.manager, "_publisher", None)
                    if publisher is not None:
                        publisher.retract_after(rolled_step)
                    # History ring: drop anything newer than the surviving
                    # committed step (belt-and-braces — refused steps were
                    # never promoted, but the ring must stay provably on
                    # the committed trajectory).
                    hist = getattr(self.manager, "_history", None)
                    if hist is not None and hasattr(hist, "retract_newer"):
                        hist.retract_newer(rolled_step)
                rec.committed = committed
                return committed

    def flush_pipeline(self, raise_on_error: bool = True) -> Optional[bool]:
        """Resolves every pending pipelined step (vote + rollback + device
        bound), oldest first; returns the last resolved verdict (False when
        the tail of the window was unwound by a refusal), or None when the
        pipeline was idle. Call at train-loop boundaries — end of run,
        before a checkpoint restore, before switching step protocols."""
        if self._pipeline is None:
            return None
        last: Optional[bool] = None
        while True:
            rec = self._pipeline.oldest()
            if rec is None:
                break
            # Records stay in the pipeline until resolved so a refusal's
            # unwind can see (and discard) the younger slots.
            last = self._resolve_pipelined_record(rec)
            self._pipeline.remove(rec)
            self._note_snapshot(rec, admitted=False)
            rec.bound_device(raise_on_error=raise_on_error)
        return last

    def _drain_pipeline_for_quorum_change(self) -> None:
        """Quorum-change hook (runs on the manager's quorum thread): fully
        resolve the WHOLE speculative window before the PG reconfigures or
        a donor send samples this replica's state — a joiner must never
        heal from an uncommitted speculative step (tpuft_check rule R7
        pins the call ordering in the manager). Safe here at every depth:
        depth-1 votes ran earlier on the same single-thread executor
        (FIFO), so their result() cannot deadlock, and depth>=2 votes ride
        the manager's dedicated commit pool — never this thread; the
        train-loop thread is parked in wait_quorum while this runs.
        Records stay in the pipeline (resolved in place, both phases
        idempotent) so the train loop still observes each step's verdict
        on its own thread."""
        schedules.point("optim.window_drain")
        if self._pipeline is None:
            return
        pending = self._pipeline.pending()
        if not pending:
            return
        # The goodput ledger attributes this span to its `drain` bucket —
        # window-resolution time spent on the quorum thread is neither
        # quorum wait nor committed compute.
        with _trace_of(self.manager).span("pipeline_drain", depth=len(pending)):
            for rec in pending:
                self._resolve_pipelined_record(rec)
                rec.bound_device(raise_on_error=False)


    def make_step_fn(
        self,
        loss_fn: Any,
        should_quantize: bool = False,
        on_quorum: Any = None,
    ):
        """Builds the fastest correct FT-DDP step for the current quorum:
        ``step_fn(*batch) -> (loss, committed)``.

        With other replica groups participating, the step is the standard
        split program — fused loss+grad dispatch, pipelined bucket gradient
        sync (:func:`~torchft_tpu.ddp.ft_allreduce_gradients`), speculative
        update under the commit barrier.

        For a **lone replica** (sole participant and a wire group of one —
        the identity-skip condition, see ``Manager.is_lone_replica``) the
        averaged gradient IS the local gradient, so nothing needs to leave
        the device: the whole loss+grad+update runs as ONE jitted XLA
        program, exactly like a plain non-FT train step. The update is
        adopted only if the commit barrier succeeds (and recomputed if the
        barrier healed this replica), so semantics match :meth:`step` — the
        fusion removes the last fixed cost the split program pays (the
        standalone optimizer dispatch), making single-group FT-DDP
        bitwise-plain compute with only the quorum + commit RPCs on top
        (the reference's 'FT for free' design point, lighthouse.rs:202-215).

        ``loss_fn(params, *batch) -> scalar``; ``on_quorum(seconds)``, when
        given, receives each step's measured quorum wait (telemetry hook).

        With ``Manager(commit_pipeline_depth=N)`` for N >= 1 (or
        ``TPUFT_COMMIT_PIPELINE_DEPTH=N|auto``) the returned step_fn runs
        the **pipelined-commit** schedule instead: up to N steps' device
        syncs and commit votes resolve while younger steps are already
        dispatched — a bounded speculative window that hides up to N
        control-plane round trips per step (``auto`` sizes N per quorum
        era from the measured RTT/step ratio). The returned ``committed``
        flag then reports the verdict of the OLDEST in-flight step
        resolved during the call — lagging dispatch by up to N steps, None
        while the window still has room; call :meth:`flush_pipeline` at
        the loop boundary for the rest. ``TPUFT_STRICT_COMMIT=1``
        overrides any pipeline depth back to the strict per-step ordering.
        """
        fused = make_jit_fused_step(self.tx, loss_fn)
        grad_fn = jax.jit(jax.value_and_grad(loss_fn))

        depth = self.manager.commit_pipeline_depth
        if depth and os.environ.get("TPUFT_STRICT_COMMIT", "0") == "1":
            logger.warning(
                "TPUFT_STRICT_COMMIT=1 overrides commit_pipeline_depth=%d: "
                "running strict per-step commits (vote only after observed "
                "completion)",
                depth,
            )
            depth = 0
        if depth:
            return self._make_pipelined_step_fn(
                fused, grad_fn, should_quantize, on_quorum, depth
            )

        def step_fn(*batch):
            self.begin_step()
            if on_quorum is not None:
                import time as _time

                t0 = _time.monotonic()
                self.manager.wait_quorum()
                on_quorum(_time.monotonic() - t0)
            else:
                self.manager.wait_quorum()
            if self.manager.errored() is None and self.manager.is_lone_replica():
                heal_count = self._heal_count
                loss, spec, recompute = self._lone_dispatch(
                    fused, grad_fn, batch
                )
                # Launch the barrier BEFORE the device sync so the commit
                # RPC rides under the readiness wait instead of after it
                # (on a high-latency device link the sync alone costs a
                # full round trip — ~70 ms on this machine's tunnel — so
                # serializing sync -> RPC was pure addition). This widens
                # .step()'s accepted envelope slightly: .step() bounds the
                # GRADS pre-vote and risks only a host-side dispatch
                # failure post-vote, while here a device-side failure of
                # the whole fused step can land after the vote was sent.
                # The blast radius in this LONE topology is bounded
                # accounting, not divergence: there is no peer to diverge
                # from, and recovery is the same supervisor-restart path
                # .step() documents — the committed counter can run one
                # step ahead of the restored state (a phantom commit).
                # Deployments that prefer the strict reference ordering
                # (vote only after observed completion; reference
                # manager.py:816-827) set TPUFT_STRICT_COMMIT=1 and pay
                # the serialized sync; a sync failure then raises before
                # any vote leaves, the pre-change semantics exactly.
                strict = os.environ.get("TPUFT_STRICT_COMMIT", "0") == "1"
                if strict:
                    _sync_device(loss)
                commit_future = self.manager.should_commit_async(None)
                if not strict:
                    try:
                        _sync_device(loss)
                    except BaseException:
                        try:
                            barrier_result = commit_future.result()
                        except Exception:
                            logger.exception(
                                "commit barrier also failed while handling a "
                                "fused-step sync failure; barrier outcome lost "
                                "to the re-raise"
                            )
                        else:
                            if barrier_result:
                                metrics.inc(
                                    "tpuft_phantom_commits_total",
                                    **_replica_labels(self.manager),
                                )
                                _trace_of(self.manager).record("phantom_commit")
                            logger.error(
                                "fused step sync failed with the commit barrier "
                                "in flight; barrier resolved committed=%s (a "
                                "committed step here advanced the step counter "
                                "without its update)",
                                barrier_result,
                            )
                        raise

                committed = self._commit_and_adopt(
                    heal_count, spec, recompute, None,
                    commit_future=commit_future,
                )
                return loss, committed
            return self._wire_step(grad_fn, batch, should_quantize)

        return step_fn

    # ------------------------------------------------------------------
    # make_step_fn seams (overridden by zero.ZeroOptimizer)
    # ------------------------------------------------------------------

    def _lone_dispatch(self, fused: Any, grad_fn: Any, batch: Any):
        """Dispatches the lone-replica step's device work; returns
        ``(loss, speculation, recompute)``. The caller owns the barrier
        ordering (strict/overlapped/pipelined) around the returned loss.
        Base: the whole loss+grad+update as ONE fused XLA program."""
        # Heals rebind self.params (never mutate buffers), so this
        # reference keeps the pre-heal state alive for the rare
        # heal-during-barrier recompute below.
        pre_params = self.params
        with metrics.timer("tpuft_update_dispatch_seconds"), _trace_of(
            self.manager
        ).span("update_dispatch", fused=True):
            loss, spec_params, spec_opt_state = fused(
                self.params, self.opt_state, *batch
            )

        def recompute():
            # Same semantics as :meth:`step` (and the reference's
            # load_state_dict + optimizer.step() sequence): the
            # gradients computed on the PRE-heal params apply to the
            # healed state.
            _, grads = grad_fn(pre_params, *batch)
            return self._jit_update(grads, self.opt_state, self.params)

        return loss, (spec_params, spec_opt_state), recompute

    def _wire_step(self, grad_fn: Any, batch: Any, should_quantize: bool):
        """The non-pipelined step with other replica groups participating:
        grad dispatch, cross-replica sync, :meth:`step`. Base: bucketed
        gradient allreduce, then the standard averaged-grads step."""
        from torchft_tpu.ddp import ft_allreduce_gradients

        loss, grads = grad_fn(self.params, *batch)
        committed = self.step(
            ft_allreduce_gradients(self.manager, grads, should_quantize)
        )
        return loss, committed

    def _wire_speculate(self, grads: Any, pre_opt: Any, pre_params: Any,
                        should_quantize: bool):
        """The pipelined wire path's speculative update: syncs ``grads``
        across replicas and computes the speculative ``(params,
        opt_state)`` from the PRE-step state; returns ``(speculation,
        recompute)``. Must complete its collectives before returning —
        the caller launches the commit vote right after, and a rank whose
        sync failed must not vote commit."""
        from torchft_tpu.ddp import ft_allreduce_gradients

        avg = ft_allreduce_gradients(self.manager, grads, should_quantize)
        spec = self._jit_update(avg, pre_opt, pre_params)

        def recompute(avg=avg):
            return self._jit_update(avg, self.opt_state, self.params)

        return spec, recompute

    def _make_pipelined_step_fn(
        self, fused: Any, grad_fn: Any, should_quantize: bool,
        on_quorum: Any, depth: int,
    ):
        """The pipelined-commit schedule (window depth N >= 1): per call —

        1. (wire path) speculatively dispatch this step's forward/backward
           and start staging the gradients to host, BEFORE any older vote
           resolves;
        2. resolve just enough of the OLDEST window slots to open one:
           with the window full, exactly one verdict per call — confirm,
           roll the live state back to that slot's pre-step snapshot
           (discarding every younger slot: their speculations chain from
           the refused one), or heal-recompute (younger slots replay their
           grads onto the healed state as they resolve in turn);
        3. quorum (a membership change drains the FULL window on the
           quorum thread before the PG reconfigures or any donor send —
           see Manager.register_quorum_change_hook);
        4. dispatch this step and tentatively adopt its speculative
           (params, opt_state) — the window grows to at most depth
           uncommitted steps;
        5. observe the resolved slots' device completion: the readiness
           round trips ride under THIS step's device execution instead of
           serializing after it (the per-step RTT this mode kills);
        6. vote with this step's device work still in flight — but only
           AFTER step (N - depth)'s completion was observed in 5, so the
           phantom-commit envelope is bounded at exactly the window depth.

        Depth 1 keeps the single-executor vote path whose FIFO ordering
        the depth-1 tests pin; depth >= 2 (and adaptive mode at any depth)
        votes through Manager.speculative_commit_async so the whole
        window's barrier RPCs overlap on the wire — that overlap is what
        hides MULTIPLE control-plane round trips per step. In adaptive
        mode the target depth is re-read from the manager every call, so
        the controller's per-era re-evaluation (and mid-era deepening)
        takes effect between steps without rebuilding the step_fn.

        The widened envelope vs the overlapped ordering: post-vote device
        failures can phantom-commit up to DEPTH steps (vote N observed
        completion only through N - depth). The blast radius is bounded
        accounting, not divergence — a failure discovered at a vote makes
        that commit fail quorum-wide, every survivor unwinds the same
        suffix of the window identically, and recovery for hard device
        failures is the same supervisor-restart + heal path the
        non-pipelined orderings document.
        """
        import time as _time

        from torchft_tpu.ddp import prefetch_gradients
        from torchft_tpu.futures import CommitPipeline

        if self._pipeline is not None and len(self._pipeline):
            self.flush_pipeline()
        manager = self.manager
        pipeline = CommitPipeline(max(1, depth))
        self._pipeline = pipeline
        if not self._pipeline_hooked:
            manager.register_quorum_change_hook(
                self._drain_pipeline_for_quorum_change
            )
            manager.register_shutdown_hook(
                lambda: self.flush_pipeline(raise_on_error=False)
            )
            self._pipeline_hooked = True
        self._next_pipelined_step = manager.current_step()
        was_wire = [False]
        # Depth 1 static keeps the legacy single-executor vote (its FIFO
        # ordering is pinned); deeper/adaptive windows vote concurrently.
        speculative_votes = manager.commit_pipeline_adaptive or depth >= 2

        def step_fn(*batch):
            target_depth = max(1, manager.commit_pipeline_depth)
            pipeline.set_depth(target_depth)
            # Next-step dispatch before any vote resolution: the wire
            # path's forward/backward depends only on the (already
            # adopted, speculative) params, so its device work and d2h
            # staging start under the vote wait + quorum RPC. A rollback
            # or heal below invalidates it — detected by identity on the
            # exact params it read — and it is recomputed.
            early = None
            if was_wire[0]:
                early_heal = self._heal_count
                early_params = self.params
                early = grad_fn(early_params, *batch)
                prefetch_gradients(early[1])

            # Resolve the oldest slots until the window has room (plus any
            # slot a rollback already unwound — zombies consume instantly).
            stall_t0 = _time.monotonic()
            first_verdict: Optional[bool] = None
            to_bound = []
            while True:
                rec = pipeline.oldest()
                if rec is None:
                    break
                zombie = (
                    rec.committed is not None
                    or rec.gen != self._speculation_gen
                )
                if not zombie and len(pipeline) < target_depth:
                    break
                verdict = self._resolve_pipelined_record(rec)
                pipeline.remove(rec)
                self._note_snapshot(rec, admitted=False)
                to_bound.append(rec)
                if first_verdict is None:
                    first_verdict = verdict
            vote_stall = _time.monotonic() - stall_t0

            self.begin_step()
            if on_quorum is not None:
                t0 = _time.monotonic()
                manager.wait_quorum()
                on_quorum(_time.monotonic() - t0)
            else:
                manager.wait_quorum()

            heal_count = self._heal_count
            pre_params, pre_opt = self.params, self.opt_state
            lone = manager.errored() is None and manager.is_lone_replica()
            was_wire[0] = not lone
            if lone:
                loss, spec, recompute = self._lone_dispatch(
                    fused, grad_fn, batch
                )
            else:
                if (
                    early is not None
                    and early_heal == self._heal_count
                    and early_params is pre_params
                ):
                    loss, grads = early
                else:
                    loss, grads = grad_fn(pre_params, *batch)
                spec, recompute = self._wire_speculate(
                    grads, pre_opt, pre_params, should_quantize
                )

            # Tentative adoption — one more slot of the uncommitted
            # window. Write-locked so a concurrent donor capture never
            # reads a torn pair.
            schedules.point("optim.speculate_adopt")
            manager.disallow_state_dict_read()
            try:
                self.params, self.opt_state = spec
            finally:
                manager.allow_state_dict_read()
            # Claim the step this slot speculates: committed + in-flight.
            # Count only UNRESOLVED slots — the quorum-thread drain
            # resolves records in place without removing them (the train
            # loop still observes each verdict), so raw occupancy can
            # overcount right after a membership change.
            claimed_step = manager.current_step() + sum(
                1 for r in pipeline.pending() if r.committed is None
            )
            self._next_pipelined_step = claimed_step + 1

            # Observe the resolved slots' device completion BEFORE this
            # step's vote leaves: the envelope invariant — vote N is sent
            # only after step (N - depth)'s completion was observed. The
            # sync rides under this step's (already dispatched) execution.
            stall_t0 = _time.monotonic()
            for done_rec in to_bound:
                done_rec.bound_device(raise_on_error=True)
            manager.observe_pipeline_step(
                vote_stall + (_time.monotonic() - stall_t0)
            )

            if speculative_votes:
                commit_future = manager.speculative_commit_async(claimed_step)
            else:
                commit_future = manager.should_commit_async(None)
            rec = _PendingStep(
                manager=manager,
                heal_count=heal_count,
                loss=loss,
                snapshot=(pre_params, pre_opt),
                recompute=recompute,
                commit_future=commit_future,
                gen=self._speculation_gen,
                claimed_step=claimed_step,
                snapshot_bytes=self._snapshot_nbytes((pre_params, pre_opt)),
            )
            pipeline.push(rec)
            self._note_snapshot(rec, admitted=True)
            _trace_of(manager).record(
                "speculate",
                step=claimed_step,
                window=len(pipeline),
                depth=target_depth,
            )
            return loss, first_verdict

        return step_fn


# Name parity with the reference export.
OptimizerWrapper = Optimizer
