"""Communication layer: rendezvous store, resizable process groups, mesh."""
