"""Subprocess-isolated process group ("Baby" PG).

Role-equivalent of the reference's ``ProcessGroupBaby*``
(/root/reference/torchft/process_group.py:1358-1983): the real comm backend
runs in a **spawned child process**, so a wedged transfer — the failure NCCL
abort exists for on GPU, and a stuck DCN socket here — can be killed with
SIGKILL without taking down the trainer or the accelerator runtime. The
parent proxies collectives over a request pipe; the child executes them on
an inner :class:`ProcessGroupTCP` and streams results (or exceptions) back
over a response pipe drained by a parent-side future-handler thread.

The reference needs shared-memory tensors + CUDA event gymnastics for this;
here host arrays pickle through the pipe — correctness first, zero-copy via
shared memory is a later optimization. The child deliberately imports only
numpy-level deps (no jax), keeping spawn latency low.
"""

from __future__ import annotations

import multiprocessing as mp
import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional, Sequence

import numpy as np

from torchft_tpu.parallel.multiprocessing import _MonitoredPipe
from torchft_tpu.parallel.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import Work

__all__ = ["ProcessGroupBaby"]


def _baby_main(req_conn, resp_conn, store_addr, replica_id, rank, world_size, timeout,
               backend):
    """Child entry: owns the real inner PG and replays parent ops."""
    req = _MonitoredPipe(req_conn)
    resp = _MonitoredPipe(resp_conn)
    if backend == "native":
        from torchft_tpu.parallel.native_pg import ProcessGroupNative

        pg = ProcessGroupNative(timeout=timeout)
    else:
        from torchft_tpu.parallel.process_group import ProcessGroupTCP

        pg = ProcessGroupTCP(timeout=timeout)
    try:
        pg.configure(store_addr, replica_id, rank, world_size)
        resp.send(("ready", None))
    except Exception as e:  # noqa: BLE001
        resp.send(("ready", RuntimeError(f"baby configure failed: {e}")))
        return
    try:
        while True:
            try:
                cmd = req.recv(timeout=3600.0)
            except (EOFError, OSError):
                return
            if cmd[0] == "shutdown":
                return
            assert cmd[0] == "func"
            _, op_id, name, args, kwargs = cmd
            try:
                work = getattr(pg, name)(*args, **kwargs)

                def on_done(fut, op_id=op_id) -> None:
                    err = fut.exception()
                    try:
                        if err is None:
                            resp.send(("result", op_id, fut.result()))
                        else:
                            resp.send(("error", op_id, RuntimeError(str(err))))
                    except (OSError, BrokenPipeError):
                        pass

                work.add_done_callback(on_done)
            except Exception as e:  # noqa: BLE001
                resp.send(("error", op_id, RuntimeError(str(e))))
    finally:
        pg.shutdown()


class ProcessGroupBaby(ProcessGroup):
    """Runs the real PG in a spawned subprocess; a hang is cured by SIGKILL
    on the child rather than process death for the trainer."""

    def __init__(self, timeout: float = 60.0, backend: str = "native") -> None:
        super().__init__()
        if backend not in ("native", "tcp"):
            raise ValueError(f"unknown baby backend {backend!r}; use 'native' or 'tcp'")
        self._timeout = timeout
        self._backend = backend
        self._rank = 0
        self._world_size = 1
        self._proc: Optional[mp.process.BaseProcess] = None
        self._req: Optional[_MonitoredPipe] = None
        self._resp: Optional[_MonitoredPipe] = None
        self._errored: Optional[Exception] = None
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._next_op_id = 0
        self._handler: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        self._teardown_child(graceful=False)
        self._errored = None
        self._rank = rank
        self._world_size = world_size

        ctx = mp.get_context("spawn")
        req_parent, req_child = ctx.Pipe()
        resp_parent, resp_child = ctx.Pipe()
        proc = ctx.Process(
            target=_baby_main,
            args=(
                req_child,
                resp_child,
                store_addr,
                replica_id,
                rank,
                world_size,
                self._timeout,
                self._backend,
            ),
            daemon=True,
            name=f"tpuft-baby-{replica_id}-{rank}",
        )
        proc.start()
        req_child.close()
        resp_child.close()
        self._proc = proc
        self._req = _MonitoredPipe(req_parent)
        self._resp = _MonitoredPipe(resp_parent)
        kind, err = self._resp.recv(timeout=self._timeout + 30)
        assert kind == "ready"
        if err is not None:
            self._errored = err
            raise err
        self._handler = threading.Thread(
            target=self._future_handler, daemon=True, name="tpuft-baby-futures"
        )
        self._handler.start()

    def _future_handler(self) -> None:
        resp = self._resp
        assert resp is not None
        while True:
            try:
                msg = resp.recv(timeout=3600.0)
            except (EOFError, OSError, TimeoutError):
                return
            kind, op_id, payload = msg
            with self._pending_lock:
                fut = self._pending.pop(op_id, None)
            if fut is None:
                continue
            if kind == "result":
                fut.set_result(payload)
            else:
                if self._errored is None:
                    self._errored = payload
                fut.set_exception(payload)

    def _teardown_child(self, graceful: bool) -> None:
        proc, req = self._proc, self._req
        self._proc = None
        if req is not None:
            if graceful:
                try:
                    req.send(("shutdown",))
                except (OSError, BrokenPipeError):
                    pass
            req.close()
        if self._resp is not None:
            self._resp.close()
        if proc is not None:
            proc.join(timeout=1.0 if graceful else 0.0)
            if proc.is_alive():
                proc.kill()  # SIGKILL: the whole point of the subprocess
                proc.join(timeout=5.0)
        # Fail any outstanding work.
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(RuntimeError("baby process group torn down"))

    def abort(self) -> None:
        self._errored = self._errored or RuntimeError("process group aborted")
        self._teardown_child(graceful=False)

    def shutdown(self) -> None:
        self._teardown_child(graceful=True)

    def errored(self) -> Optional[Exception]:
        return self._errored

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    def num_active_work(self) -> int:
        with self._pending_lock:
            return len(self._pending)

    # -- op proxying -------------------------------------------------------

    def _run_func(self, name: str, *args: Any, **kwargs: Any) -> Work:
        if self._errored is not None:
            raise RuntimeError(f"process group in error state: {self._errored}")
        if self._req is None or self._proc is None or not self._proc.is_alive():
            raise RuntimeError("baby process group not configured / child dead")
        fut: Future = Future()
        with self._pending_lock:
            op_id = self._next_op_id
            self._next_op_id += 1
            self._pending[op_id] = fut
        try:
            self._req.send(("func", op_id, name, args, kwargs))
        except (OSError, BrokenPipeError) as e:
            with self._pending_lock:
                self._pending.pop(op_id, None)
            self._errored = RuntimeError(f"baby pipe broken: {e}")
            raise self._errored from e
        return Work(fut)

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._run_func("allreduce", [np.asarray(a) for a in arrays], op)

    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._run_func("allgather", [np.asarray(a) for a in arrays])

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        return self._run_func("broadcast", [np.asarray(a) for a in arrays], root)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._run_func("reduce_scatter", [np.asarray(a) for a in arrays], op)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._run_func("alltoall", [np.asarray(a) for a in arrays])

    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work:
        return self._run_func("send", [np.asarray(a) for a in arrays], dst, tag)

    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        return self._run_func("recv", [np.asarray(a) for a in shapes_like], src, tag)

    def barrier(self) -> Work:
        return self._run_func("barrier")
