"""Subprocess-isolated process group ("Baby" PG).

Role-equivalent of the reference's ``ProcessGroupBaby*``
(/root/reference/torchft/process_group.py:1358-1983): the real comm backend
runs in a **spawned child process**, so a wedged transfer — the failure NCCL
abort exists for on GPU, and a stuck DCN socket here — can be killed with
SIGKILL without taking down the trainer or the accelerator runtime. The
parent proxies collectives over a request pipe; the child executes them on
an inner :class:`ProcessGroupTCP` and streams results (or exceptions) back
over a response pipe drained by a parent-side future-handler thread.

Arrays ≥ 1 MiB cross the process boundary through **shared memory** (the
reference's share_memory_ enforcement, process_group.py:1338-1349): the
sender stages the bytes in a SharedMemory segment and ships only a
descriptor through the pipe; the receiver maps the segment as a zero-copy
numpy view. Small arrays still pickle through the pipe (cheaper than a
segment per scalar). The child deliberately imports only numpy-level deps
(no jax), keeping spawn latency low.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import threading
import weakref
from concurrent.futures import Future
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.parallel.multiprocessing import _MonitoredPipe
from torchft_tpu.parallel.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)

__all__ = ["ProcessGroupBaby"]

# Arrays at or above this size ride shared memory instead of the pickle pipe.
SHM_THRESHOLD_BYTES = 1 << 20


@dataclass
class _ShmRef:
    """Descriptor of an array staged in a SharedMemory segment."""

    name: str
    shape: Tuple[int, ...]
    dtype: str  # np.dtype name (ml_dtypes resolve via registry)


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Detaches an ATTACHED (not created) segment from this process's
    resource tracker — the creator owns unlink; double-tracking makes the
    tracker spuriously destroy or warn about the segment at exit."""
    try:  # pragma: no cover - stdlib-version dependent
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:  # noqa: BLE001
        pass


def _stage_arrays(
    arrays: Sequence[np.ndarray], segments: List[shared_memory.SharedMemory]
) -> List[Any]:
    """Replaces large arrays with _ShmRef descriptors; appends the created
    segments (caller owns close+unlink after the op completes)."""
    staged: List[Any] = []
    for array in arrays:
        array = np.ascontiguousarray(array)
        if array.nbytes >= SHM_THRESHOLD_BYTES:
            shm = shared_memory.SharedMemory(create=True, size=array.nbytes)
            dst = np.ndarray((array.nbytes,), dtype=np.uint8, buffer=shm.buf)
            dst[:] = np.atleast_1d(array).view(np.uint8).reshape(-1)
            segments.append(shm)
            staged.append(_ShmRef(shm.name, tuple(array.shape), np.dtype(array.dtype).name))
        else:
            staged.append(array)
    return staged


def _map_arrays(entries: Sequence[Any], owned: bool) -> List[np.ndarray]:
    """Materializes a staged list: _ShmRef entries become zero-copy views
    over the mapped segment, kept alive by a finalizer on the array. With
    ``owned`` the finalizer also unlinks (receiver of a result owns the
    segment); otherwise the creator unlinks."""
    out: List[np.ndarray] = []
    for entry in entries:
        if isinstance(entry, _ShmRef):
            shm = shared_memory.SharedMemory(name=entry.name, create=False)
            _untrack(shm)
            dtype = _resolve_dtype(entry.dtype)
            flat = np.ndarray(
                (int(np.prod(entry.shape or (1,))) * dtype.itemsize,),
                dtype=np.uint8,
                buffer=shm.buf,
            )
            array = flat.view(dtype).reshape(entry.shape)
            if owned:
                weakref.finalize(array, _cleanup_shm, shm, True)
            else:
                weakref.finalize(array, _cleanup_shm, shm, False)
            out.append(array)
        else:
            out.append(entry)
    return out


def _cleanup_shm(shm: shared_memory.SharedMemory, unlink: bool) -> None:
    try:
        shm.close()
        if unlink:
            shm.unlink()
    except Exception:  # noqa: BLE001
        pass


def _stage_result(value: Any, segments: List[shared_memory.SharedMemory]) -> Any:
    """Recursively stages large arrays in nested op results (allgather
    returns lists of lists)."""
    if isinstance(value, np.ndarray):
        return _stage_arrays([value], segments)[0]
    if isinstance(value, (list, tuple)):
        return type(value)(_stage_result(v, segments) for v in value)
    return value


def _map_result(value: Any) -> Any:
    """Inverse of :func:`_stage_result` on the receiving side; the receiver
    owns the segments (finalizers unlink)."""
    if isinstance(value, _ShmRef):
        return _map_arrays([value], owned=True)[0]
    if isinstance(value, (list, tuple)):
        return type(value)(_map_result(v) for v in value)
    return value


def _discard_result(value: Any) -> None:
    """Unlinks the segments of a result nobody will consume (the op's
    future was already dropped by abort/teardown) — without this, the
    child's transferred-ownership segments would orphan in /dev/shm."""
    if isinstance(value, _ShmRef):
        try:
            shm = shared_memory.SharedMemory(name=value.name, create=False)
        except FileNotFoundError:
            return
        _untrack(shm)
        _cleanup_shm(shm, unlink=True)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _discard_result(v)


def _baby_main(req_conn, resp_conn, store_addr, replica_id, rank, world_size, timeout,
               backend):
    """Child entry: owns the real inner PG and replays parent ops."""
    req = _MonitoredPipe(req_conn)
    resp = _MonitoredPipe(resp_conn)
    if backend == "native":
        from torchft_tpu.parallel.native_pg import ProcessGroupNative

        pg = ProcessGroupNative(timeout=timeout)
    else:
        from torchft_tpu.parallel.process_group import ProcessGroupTCP

        pg = ProcessGroupTCP(timeout=timeout)
    try:
        pg.configure(store_addr, replica_id, rank, world_size)
        resp.send(("ready", None))
    except Exception as e:  # noqa: BLE001
        resp.send(("ready", RuntimeError(f"baby configure failed: {e}")))
        return
    try:
        while True:
            try:
                cmd = req.recv(timeout=3600.0)
            except (EOFError, OSError):
                return
            if cmd[0] == "shutdown":
                return
            if cmd[0] == "wedge":
                # Test-only chaos: simulate a wedged transfer (the hang the
                # Baby isolation exists to cure — parent must SIGKILL us).
                import time as _time

                _time.sleep(10**9)
            assert cmd[0] == "func"
            _, op_id, name, args, kwargs = cmd
            try:
                # First positional arg of every collective is the array list;
                # large entries arrive as _ShmRef and map zero-copy.
                if args and isinstance(args[0], (list, tuple)):
                    args = ([*_map_arrays(args[0], owned=False)], *args[1:])
                work = getattr(pg, name)(*args, **kwargs)

                def on_done(fut, op_id=op_id) -> None:
                    try:
                        err = fut.exception()
                        if err is None:
                            segments: list = []
                            result = _stage_result(fut.result(), segments)
                            # Parent owns the result segments (its mapped
                            # arrays unlink on garbage collection); drop the
                            # child's own handles after the send.
                            resp.send(("result", op_id, result))
                            for shm in segments:
                                # Ownership transferred to the parent: drop
                                # this side's handle AND tracker entry, or
                                # the child tracker would unlink live
                                # segments at child exit.
                                _untrack(shm)
                                try:
                                    shm.close()
                                except Exception:  # noqa: BLE001
                                    pass
                        else:
                            resp.send(("error", op_id, RuntimeError(str(err))))
                    except (OSError, BrokenPipeError):
                        pass  # parent is gone; nothing to report to
                    except BaseException as e:  # noqa: BLE001
                        # Result staging failed (e.g. shm exhaustion): the
                        # parent must still get an answer or its future
                        # hangs until timeout.
                        try:
                            resp.send(
                                ("error", op_id,
                                 RuntimeError(f"baby result staging failed: {e}"))
                            )
                        except Exception:  # noqa: BLE001
                            pass

                work.add_done_callback(on_done)
            except Exception as e:  # noqa: BLE001
                resp.send(("error", op_id, RuntimeError(str(e))))
    finally:
        pg.shutdown()


class ProcessGroupBaby(ProcessGroup):
    """Runs the real PG in a spawned subprocess; a hang is cured by SIGKILL
    on the child rather than process death for the trainer."""

    def __init__(self, timeout: float = 60.0, backend: str = "native") -> None:
        super().__init__()
        if backend not in ("native", "tcp"):
            raise ValueError(f"unknown baby backend {backend!r}; use 'native' or 'tcp'")
        self._timeout = timeout
        self._backend = backend
        self._rank = 0
        self._world_size = 1
        self._proc: Optional[mp.process.BaseProcess] = None
        self._req: Optional[_MonitoredPipe] = None
        self._resp: Optional[_MonitoredPipe] = None
        self._errored: Optional[Exception] = None
        self._pending: Dict[int, Future] = {}
        self._op_segments: Dict[int, List[shared_memory.SharedMemory]] = {}
        self._pending_lock = threading.Lock()
        self._next_op_id = 0
        self._handler: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        self._teardown_child(graceful=False)
        self._errored = None
        self._rank = rank
        self._world_size = world_size

        ctx = mp.get_context("spawn")
        req_parent, req_child = ctx.Pipe()
        resp_parent, resp_child = ctx.Pipe()
        proc = ctx.Process(
            target=_baby_main,
            args=(
                req_child,
                resp_child,
                store_addr,
                replica_id,
                rank,
                world_size,
                self._timeout,
                self._backend,
            ),
            daemon=True,
            name=f"tpuft-baby-{replica_id}-{rank}",
        )
        proc.start()
        req_child.close()
        resp_child.close()
        self._proc = proc
        self._req = _MonitoredPipe(req_parent)
        self._resp = _MonitoredPipe(resp_parent)
        kind, err = self._resp.recv(timeout=self._timeout + 30)
        assert kind == "ready"
        if err is not None:
            self._errored = err
            raise err
        self._handler = threading.Thread(
            target=self._future_handler, daemon=True, name="tpuft-baby-futures"
        )
        self._handler.start()

    def _future_handler(self) -> None:
        resp = self._resp
        assert resp is not None
        while True:
            try:
                msg = resp.recv(timeout=3600.0)
            except (EOFError, OSError, TimeoutError):
                return
            except BaseException as e:  # noqa: BLE001 — undecodable message
                # e.g. an unpicklable payload: the pipe is unusable; exit
                # like EOF (pending ops fail via their own timeouts).
                logger.exception("baby future-handler: pipe recv failed: %s", e)
                return
            kind, op_id, payload = msg
            fut: Optional[Future] = None
            try:
                with self._pending_lock:
                    fut = self._pending.pop(op_id, None)
                    segments = self._op_segments.pop(op_id, ())
                # The op is complete: the request segments (this side
                # created) can be released.
                for shm in segments:
                    _cleanup_shm(shm, unlink=True)
                if fut is None:
                    if kind == "result":
                        _discard_result(payload)
                    continue
                if kind == "result":
                    fut.set_result(_map_result(payload))
                else:
                    if self._errored is None:
                        self._errored = payload
                    fut.set_exception(payload)
            except BaseException as e:  # noqa: BLE001 — handler must survive
                # A result-mapping failure (e.g. a vanished shm segment)
                # must fail ITS op, not kill the handler thread — a dead
                # handler hangs every later op until timeout.
                if fut is not None and not fut.done():
                    fut.set_exception(
                        RuntimeError(f"baby result handling failed: {e}")
                    )
                logger.exception("baby future-handler: op %s failed: %s", op_id, e)

    def _teardown_child(self, graceful: bool) -> None:
        proc, req = self._proc, self._req
        self._proc = None
        if req is not None:
            if graceful:
                try:
                    req.send(("shutdown",))
                except (OSError, BrokenPipeError):
                    pass
            req.close()
        if self._resp is not None:
            self._resp.close()
        if proc is not None:
            proc.join(timeout=1.0 if graceful else 0.0)
            if proc.is_alive():
                proc.kill()  # SIGKILL: the whole point of the subprocess
                proc.join(timeout=5.0)
        # Fail any outstanding work; release its staged segments.
        with self._pending_lock:
            pending, self._pending = self._pending, {}
            segments, self._op_segments = self._op_segments, {}
        for shms in segments.values():
            for shm in shms:
                _cleanup_shm(shm, unlink=True)
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(RuntimeError("baby process group torn down"))

    def abort(self) -> None:
        self._errored = self._errored or RuntimeError("process group aborted")
        self._teardown_child(graceful=False)

    def shutdown(self) -> None:
        self._teardown_child(graceful=True)

    def errored(self) -> Optional[Exception]:
        return self._errored

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    def num_active_work(self) -> int:
        with self._pending_lock:
            return len(self._pending)

    def _inject_wedge(self) -> None:
        """Test-only: wedges the child's op loop forever (a hung-transfer
        simulation). The cure is abort() → SIGKILL → reconfigure."""
        assert self._req is not None
        self._req.send(("wedge",))

    # -- op proxying -------------------------------------------------------

    def _run_func(self, name: str, *args: Any, **kwargs: Any) -> Work:
        if self._errored is not None:
            raise RuntimeError(f"process group in error state: {self._errored}")
        if self._req is None or self._proc is None or not self._proc.is_alive():
            raise RuntimeError("baby process group not configured / child dead")
        # Large arrays cross via shared memory (descriptor on the pipe).
        segments: List[shared_memory.SharedMemory] = []
        if args and isinstance(args[0], (list, tuple)):
            args = ([*_stage_arrays(args[0], segments)], *args[1:])
        fut: Future = Future()
        with self._pending_lock:
            op_id = self._next_op_id
            self._next_op_id += 1
            self._pending[op_id] = fut
            if segments:
                self._op_segments[op_id] = segments
        try:
            self._req.send(("func", op_id, name, args, kwargs))
        except (OSError, BrokenPipeError) as e:
            with self._pending_lock:
                self._pending.pop(op_id, None)
                self._op_segments.pop(op_id, None)
            for shm in segments:
                _cleanup_shm(shm, unlink=True)
            self._errored = RuntimeError(f"baby pipe broken: {e}")
            raise self._errored from e
        return Work(fut)

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._run_func("allreduce", [np.asarray(a) for a in arrays], op)

    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._run_func("allgather", [np.asarray(a) for a in arrays])

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        return self._run_func("broadcast", [np.asarray(a) for a in arrays], root)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._run_func("reduce_scatter", [np.asarray(a) for a in arrays], op)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._run_func("alltoall", [np.asarray(a) for a in arrays])

    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work:
        return self._run_func("send", [np.asarray(a) for a in arrays], dst, tag)

    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        return self._run_func("recv", [np.asarray(a) for a in shapes_like], src, tag)

    def barrier(self) -> Work:
        return self._run_func("barrier")
