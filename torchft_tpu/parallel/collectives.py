"""Quantized collectives: 8-bit allreduce / reduce_scatter over any PG.

Role-equivalent of the reference's ``torchft/collectives.py:159-415``:

  allreduce_quantized:
    quantize -> alltoall of per-rank block chunks -> fused local
    dequantize-reduce-requantize -> allgather -> dequantize into outputs

Wire traffic is a quantized payload (fp8 e4m3 / int8, matching the
reference's fp8-on-SM90+/int8-below dual format, or opt-in packed int4 at
half the bytes — ``TPUFT_WIRE_DTYPE``) + f32
per-block scales, ~4x smaller than f32 both directions. SUM/AVG only, like
the reference. The quantization math lives in
:mod:`torchft_tpu.ops.quantization` (numpy here; Pallas kernels for the
on-device path).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Sequence, Tuple

import numpy as np

from torchft_tpu import metrics, tracing
from torchft_tpu.ops import quantization as q
from torchft_tpu.parallel.process_group import ProcessGroup, ReduceOp
from torchft_tpu.utils.transfer import prefetch_to_host
from torchft_tpu.work import Work

__all__ = [
    "allreduce_quantized",
    "reduce_scatter_quantized",
    "allreduce_quantized_wire",
]

# Multi-stage pipelines (alltoall -> reduce -> allgather) must not block the
# PG's single op-worker thread waiting on ops they themselves enqueue, so
# they run on a dedicated pool (the reference uses a side CUDA stream +
# future chain for the same reason, collectives.py:308-330).
_PIPELINE_POOL = ThreadPoolExecutor(max_workers=8, thread_name_prefix="tpuft-quant")


def _quantize_and_chunk(
    arrays: Sequence[np.ndarray], world_size: int, wire: str
) -> Tuple[List[np.ndarray], List[dict]]:
    """Quantizes each array and splits its blocks into world_size chunks;
    returns per-rank packed wire buffers + per-array recovery metadata."""
    metas = []
    # chunks[rank] collects this rank's slice of every array.
    per_rank_parts: List[List[np.ndarray]] = [[] for _ in range(world_size)]
    for array in arrays:
        array = np.asarray(array)
        payload, scales = q.quantize_blocks(array, wire=wire)
        n_blocks = payload.shape[0]
        # Pad the block count so every rank owns an equal chunk.
        pad = (-n_blocks) % world_size
        if pad:
            payload = np.concatenate(
                [payload, np.zeros((pad, payload.shape[1]), dtype=payload.dtype)]
            )
            scales = np.concatenate([scales, np.ones(pad, dtype=scales.dtype)])
        blocks_per_rank = payload.shape[0] // world_size
        metas.append(
            {
                "shape": array.shape,
                "dtype": array.dtype,
                "n_blocks": n_blocks,
                "blocks_per_rank": blocks_per_rank,
                "wire": wire,
            }
        )
        for rank in range(world_size):
            lo, hi = rank * blocks_per_rank, (rank + 1) * blocks_per_rank
            per_rank_parts[rank].append(q.pack_arrays(payload[lo:hi], scales[lo:hi]))
    wire_bufs = [np.concatenate(parts) for parts in per_rank_parts]
    return wire_bufs, metas


def _split_wire(buf: np.ndarray, metas: List[dict]) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Splits a packed per-rank buffer back into (payload, scales) per array."""
    out = []
    offset = 0
    for meta in metas:
        nb = meta["blocks_per_rank"]
        length = q.WIRE_HEADER_BYTES + nb * 4 + nb * q.payload_cols(meta["wire"])
        payload, scales = q.unpack_arrays(
            buf[offset : offset + length], nb, wire=meta["wire"]
        )
        out.append((payload, scales))
        offset += length
    return out


def allreduce_quantized(
    arrays: Sequence[np.ndarray],
    reduce_op: ReduceOp,
    pg: ProcessGroup,
    wire_dtype: "str | None" = None,
) -> Work:
    """8-bit allreduce (reference collectives.py:297-415). Resolves to the
    reduced arrays in their original dtypes/shapes. SUM and AVG only;
    ``wire_dtype`` is "fp8"/"int8"/"int4" (default ``TPUFT_WIRE_DTYPE``/fp8 — all
    replicas must agree, exactly as the reference's SM90 autodetect picks
    one format per job)."""
    if reduce_op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"unsupported reduce op for quantized allreduce: {reduce_op}")
    wire_dtype = q._resolve_wire(wire_dtype)
    arrays = [np.asarray(a) for a in arrays]
    world_size = pg.size()
    rank = pg.rank()

    if world_size == 1:
        result = [a.copy() for a in arrays]
        return Work.completed(result)

    wire_bufs, metas = _quantize_and_chunk(arrays, world_size, wire_dtype)
    metrics.inc(
        "tpuft_wire_bytes_total",
        sum(buf.nbytes for buf in wire_bufs),
        path="quantized",
    )

    def pipeline() -> List[np.ndarray]:
        pipeline_t0 = time.perf_counter()
        # 1. alltoall: rank r receives everyone's chunk r.
        received = pg.alltoall(wire_bufs).wait()
        # 2. fused dequant-reduce-requant per array chunk.
        per_rank = [_split_wire(buf, metas) for buf in received]
        my_reduced: List[np.ndarray] = []
        for idx, meta in enumerate(metas):
            payloads = [per_rank[r][idx][0] for r in range(world_size)]
            scales = [per_rank[r][idx][1] for r in range(world_size)]
            out_payload, out_scales = q.reduce_quantized(payloads, scales)
            if reduce_op == ReduceOp.AVG:
                out_scales = (out_scales / world_size).astype(np.float32)
            my_reduced.append(q.pack_arrays(out_payload, out_scales))
        # 3. allgather the reduced chunks.
        gathered = pg.allgather([np.concatenate(my_reduced)]).wait()
        # 4. reassemble + dequantize.
        outputs: List[np.ndarray] = []
        splits = [_split_wire(bufs[0], metas) for bufs in gathered]
        for idx, meta in enumerate(metas):
            payload = np.concatenate([splits[r][idx][0] for r in range(world_size)])
            scales = np.concatenate([splits[r][idx][1] for r in range(world_size)])
            payload = payload[: meta["n_blocks"]]
            scales = scales[: meta["n_blocks"]]
            outputs.append(
                q.dequantize_blocks(payload, scales, meta["shape"], meta["dtype"])
            )
        pipeline_dt = time.perf_counter() - pipeline_t0
        metrics.observe("tpuft_quantized_pipeline_seconds", pipeline_dt)
        tracing.record(
            "wire_bucket", ph="X", dur=pipeline_dt, path="quantized"
        )
        return outputs

    return Work(_PIPELINE_POOL.submit(pipeline))


def reduce_scatter_quantized(
    arrays: Sequence[np.ndarray],
    reduce_op: ReduceOp,
    pg: ProcessGroup,
    wire_dtype: "str | None" = None,
) -> Work:
    """8-bit reduce_scatter (reference collectives.py:159-294): each rank
    gets its chunk of the reduced result (split along blocks, returned
    flat)."""
    if reduce_op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"unsupported reduce op for quantized reduce_scatter: {reduce_op}")
    wire_dtype = q._resolve_wire(wire_dtype)
    arrays = [np.asarray(a) for a in arrays]
    world_size = pg.size()

    if world_size == 1:
        return Work.completed([a.astype(np.float32).reshape(-1) for a in arrays])

    wire_bufs, metas = _quantize_and_chunk(arrays, world_size, wire_dtype)

    def pipeline() -> List[np.ndarray]:
        received = pg.alltoall(wire_bufs).wait()
        per_rank = [_split_wire(buf, metas) for buf in received]
        outputs: List[np.ndarray] = []
        for idx, meta in enumerate(metas):
            payloads = [per_rank[r][idx][0] for r in range(world_size)]
            scales = [per_rank[r][idx][1] for r in range(world_size)]
            out_payload, out_scales = q.reduce_quantized(payloads, scales)
            if reduce_op == ReduceOp.AVG:
                out_scales = (out_scales / world_size).astype(np.float32)
            chunk = q._decode_payload_np(out_payload) * out_scales[:, None]
            outputs.append(chunk.reshape(-1))
        return outputs

    return Work(_PIPELINE_POOL.submit(pipeline))


def allreduce_quantized_wire(
    payload: np.ndarray,
    scales: np.ndarray,
    reduce_op: ReduceOp,
    pg: ProcessGroup,
) -> Work:
    """Allreduce of ALREADY-quantized data, staying quantized end to end.

    The caller quantized on device (Pallas) and ships only the 8-bit
    payload (fp8/int8/packed int4 — read from the payload dtype, so explicit-wire
    codecs never mismatch the env default) + f32 block scales across the
    host boundary; this exchanges the chunks (alltoall), does the fused
    dequant-reduce-requant per chunk, allgathers, and resolves to the
    reduced (payload, scales) pair for device-side dequantization. AVG
    folds into the scales (free).
    """
    if reduce_op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"unsupported reduce op: {reduce_op}")
    world_size = pg.size()
    # The wire format is whatever the caller's device codec produced — read
    # it from the payload dtype (no fetch needed) so a codec built with an
    # explicit wire= never mismatches the env default.
    wire = q.wire_of(payload)
    # Kick off the device→host copies now (non-blocking) so they progress
    # while this call returns and the caller keeps dispatching inner steps.
    prefetch_to_host((payload, scales))

    def pipeline():
        # The device->host fetch completes HERE, on the pipeline thread, so
        # a streaming caller (fragment_sync_delay > 0) overlaps the transfer
        # with further inner steps.
        payload_h = np.asarray(payload)
        scales_h = np.asarray(scales, dtype=np.float32)
        n_blocks = payload_h.shape[0]

        if world_size == 1:
            out_scales = scales_h / world_size if reduce_op == ReduceOp.AVG else scales_h
            return payload_h.copy(), out_scales.astype(np.float32)

        pad = (-n_blocks) % world_size
        if pad:
            payload_p = np.concatenate(
                [payload_h, np.zeros((pad, payload_h.shape[1]), dtype=payload_h.dtype)]
            )
            scales_p = np.concatenate([scales_h, np.ones(pad, dtype=scales_h.dtype)])
        else:
            payload_p, scales_p = payload_h, scales_h
        blocks_per_rank = payload_p.shape[0] // world_size
        wire_bufs = [
            q.pack_arrays(
                payload_p[r * blocks_per_rank : (r + 1) * blocks_per_rank],
                scales_p[r * blocks_per_rank : (r + 1) * blocks_per_rank],
            )
            for r in range(world_size)
        ]
        metrics.inc(
            "tpuft_wire_bytes_total",
            sum(buf.nbytes for buf in wire_bufs),
            path="quantized",
        )
        pipeline_t0 = time.perf_counter()
        received = pg.alltoall(wire_bufs).wait()
        payloads, chunk_scales = zip(
            *(q.unpack_arrays(buf, blocks_per_rank, wire=wire) for buf in received)
        )
        out_payload, out_scales = q.reduce_quantized(list(payloads), list(chunk_scales))
        if reduce_op == ReduceOp.AVG:
            out_scales = (out_scales / world_size).astype(np.float32)
        gathered = pg.allgather([q.pack_arrays(out_payload, out_scales)]).wait()
        full_payloads = []
        full_scales = []
        for bufs in gathered:
            p_chunk, s_chunk = q.unpack_arrays(bufs[0], blocks_per_rank, wire=wire)
            full_payloads.append(p_chunk)
            full_scales.append(s_chunk)
        payload_out = np.concatenate(full_payloads)[:n_blocks]
        scales_out = np.concatenate(full_scales)[:n_blocks]
        pipeline_dt = time.perf_counter() - pipeline_t0
        metrics.observe("tpuft_quantized_pipeline_seconds", pipeline_dt)
        tracing.record(
            "wire_bucket", ph="X", dur=pipeline_dt, path="quantized"
        )
        return payload_out, scales_out

    return Work(_PIPELINE_POOL.submit(pipeline))
