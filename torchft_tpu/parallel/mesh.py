"""Fault-tolerant device mesh: intra-slice sharding x FT replica axis.

Role-equivalent of the reference's ``ManagedDeviceMesh`` / ``ft_init_device_mesh``
(/root/reference/torchft/device_mesh.py:307-340): the reference builds a real
DeviceMesh *without* the replicate dim and re-inserts it virtually, lying
about its size so FSDP/TP code composes with a dynamically-resizing replica
axis.

The TPU translation: intra-slice parallelism (fsdp/tp/sp) is a real
``jax.sharding.Mesh`` over the slice's devices — XLA inserts those
collectives inside the jitted step over ICI. The replica axis is *not* a
jax mesh dim: it is the manager's resizable process group over DCN, so
membership changes never force an XLA recompile. :class:`FTMesh` exposes the
composite view (replica axis size = live participant count) and
:func:`ft_allreduce_sharded` performs the HSDP gradient sync: each host
reduces its *local shards* with the corresponding hosts of other replica
groups, keeping sharded arrays sharded end to end.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from torchft_tpu.manager import Manager

__all__ = ["FTMesh", "ft_init_device_mesh", "ft_allreduce_sharded"]


class FTMesh:
    """Composite mesh view: a real intra-slice Mesh plus the virtual,
    dynamically-sized replica axis managed by the fault-tolerance layer."""

    def __init__(
        self,
        manager: Manager,
        mesh: Mesh,
        replica_axis_name: str = "replica",
    ) -> None:
        self.manager = manager
        self.mesh = mesh
        self.replica_axis_name = replica_axis_name
        if replica_axis_name in mesh.axis_names:
            raise ValueError(
                f"replica axis {replica_axis_name!r} must not be a jax mesh dim: "
                "it is virtual (resized per quorum without recompiling)"
            )

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return (self.replica_axis_name, *self.mesh.axis_names)

    def size(self, axis: Optional[str] = None) -> int:
        """Axis size; the replica axis reports the live participant count
        (0 participants reads as 1, the ManagedDeviceMesh lie —
        reference device_mesh.py:169-184)."""
        if axis is None:
            return self.size(self.replica_axis_name) * int(
                np.prod([self.mesh.shape[a] for a in self.mesh.axis_names])
            )
        if axis == self.replica_axis_name:
            return max(self.manager.num_participants(), 1)
        return self.mesh.shape[axis]

    def replica_rank(self) -> Optional[int]:
        return self.manager.participating_rank()

    def sharding(self, *spec: Any) -> NamedSharding:
        """NamedSharding over the intra-slice mesh. The replica axis never
        appears in specs (replicated-by-construction across groups)."""
        for entry in spec:
            names = entry if isinstance(entry, (tuple, list)) else (entry,)
            for name in names:
                if name == self.replica_axis_name:
                    raise ValueError(
                        "shard over the replica axis via the manager "
                        "(ft_allreduce_sharded), not NamedSharding"
                    )
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def __repr__(self) -> str:
        return (
            f"FTMesh(replica={self.replica_axis_name}(dynamic), "
            f"mesh={dict(self.mesh.shape)})"
        )


def ft_init_device_mesh(
    manager: Manager,
    mesh_shape: Sequence[int],
    axis_names: Sequence[str],
    replica_axis_name: str = "replica",
    devices: Optional[Sequence[Any]] = None,
) -> FTMesh:
    """Builds the intra-slice Mesh (without the replica dim) and wraps it in
    an :class:`FTMesh` (reference ft_init_device_mesh, device_mesh.py:307-340).

    ``mesh_shape``/``axis_names`` describe only the intra-slice axes; pass
    ``devices`` to restrict to a subset (e.g. a slice's local devices).
    """
    if len(mesh_shape) != len(axis_names):
        raise ValueError("mesh_shape and axis_names must align")
    devices = list(devices if devices is not None else jax.devices())
    needed = int(np.prod(mesh_shape))
    if len(devices) < needed:
        raise ValueError(f"need {needed} devices, have {len(devices)}")
    device_grid = np.array(devices[:needed]).reshape(tuple(mesh_shape))
    return FTMesh(manager, Mesh(device_grid, tuple(axis_names)), replica_axis_name)


def ft_allreduce_sharded(
    manager: Manager, grads: Any, should_quantize: bool = False
) -> Any:
    """HSDP gradient sync: averages each leaf across replica groups while
    preserving its intra-slice sharding.

    For every jax.Array leaf, the host's addressable shards are staged to
    host memory, reduced shard-by-shard with the corresponding shards on the
    other replica groups (one flat payload on the manager's process group),
    and scattered back onto the same devices/sharding. Shard layouts must
    match across groups — guaranteed when every group runs the same model
    under the same intra-slice mesh, the invariant HSDP already requires.
    """
    from torchft_tpu.ddp import _single_participant_identity

    if _single_participant_identity(manager):
        return grads

    leaves, treedef = jax.tree_util.tree_flatten(grads)

    # Stage: per-leaf list of (device, host_shard) in index order.
    staged: List[Dict[str, Any]] = []
    flat_arrays: List[np.ndarray] = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            # Deterministic, group-independent order: by the shard's index
            # window (device ids differ across replica groups).
            shards = sorted(
                leaf.addressable_shards,
                key=lambda s: tuple(
                    (sl.start or 0, sl.stop if sl.stop is not None else -1)
                    for sl in s.index
                ),
            )
            entry = {
                "type": "sharded",
                "sharding": leaf.sharding,
                "shape": leaf.shape,
                "dtype": leaf.dtype,
                "devices": [s.device for s in shards],
                "indices": [s.index for s in shards],
                "count": len(shards),
            }
            staged.append(entry)
            for s in shards:
                flat_arrays.append(np.asarray(s.data))
        else:
            staged.append({"type": "plain", "count": 1})
            flat_arrays.append(np.asarray(leaf))

    work = manager.allreduce_pytree(flat_arrays, should_quantize=should_quantize)
    averaged: List[np.ndarray] = work.wait()

    # Scatter back preserving shardings.
    out_leaves: List[Any] = []
    cursor = 0
    for entry, orig in zip(staged, leaves):
        if entry["type"] == "plain":
            host = averaged[cursor]
            cursor += 1
            if isinstance(orig, jax.Array):
                out_leaves.append(jax.device_put(host, orig.sharding))
            else:
                out_leaves.append(host)
            continue
        shard_arrays = averaged[cursor : cursor + entry["count"]]
        cursor += entry["count"]
        buffers = [
            jax.device_put(host, device)
            for host, device in zip(shard_arrays, entry["devices"])
        ]
        out_leaves.append(
            jax.make_array_from_single_device_arrays(
                entry["shape"], entry["sharding"], buffers
            )
        )
    return jax.tree_util.tree_unflatten(treedef, out_leaves)
