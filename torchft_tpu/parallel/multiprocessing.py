"""Monitored pipe for subprocess process groups.

Role-equivalent of the reference's ``torchft/multiprocessing.py``: a
Connection wrapper whose ``recv`` enforces a timeout and re-raises
exceptions received from the peer, so a wedged child can never silently
hang the parent.
"""

from __future__ import annotations

from multiprocessing.connection import Connection
from typing import Any

__all__ = ["_MonitoredPipe"]


class _MonitoredPipe:
    def __init__(self, pipe: "Connection") -> None:
        self._pipe = pipe

    def send(self, obj: Any) -> None:
        self._pipe.send(obj)

    def recv(self, timeout: float) -> Any:
        """Receives one message; raises TimeoutError on silence past
        ``timeout`` and re-raises Exception payloads from the peer."""
        if not self._pipe.poll(timeout):
            raise TimeoutError(f"pipe recv timed out after {timeout}s")
        item = self._pipe.recv()
        if isinstance(item, Exception):
            raise item
        return item

    def close(self) -> None:
        try:
            self._pipe.close()
        except OSError:
            pass

    def closed(self) -> bool:
        return self._pipe.closed
