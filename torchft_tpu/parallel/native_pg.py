"""ProcessGroupNative: the C++ collective engine behind the PG interface.

The data-plane counterpart of the reference's native Gloo backend: ring
allreduce, ring allgather, linear broadcast, and pairwise alltoall run in
C++ (native/src/collectives.cc) over a full TCP mesh, with numpy arrays
passed zero-copy via ctypes. Calls release the GIL, so collectives overlap
Python-side training for real.

Same resizable semantics as :class:`ProcessGroupTCP`: ``configure`` under a
fresh store prefix per quorum, sticky ``errored()``, ``abort`` closes the
mesh and fails in-flight ops. Same determinism contract: every rank's
results are bitwise identical.
"""

from __future__ import annotations

import ctypes
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

import numpy as np

from torchft_tpu import _native
from torchft_tpu.utils import flight_recorder as fr
from torchft_tpu.parallel.process_group import (
    ProcessGroup,
    ReduceOp,
    pickle_dumps_arrays,
    pickle_loads_arrays,
)
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)

__all__ = ["ProcessGroupNative"]

_DTYPE_CODES = {}  # populated lazily (ml_dtypes import)

_REDUCE_CODES = {
    ReduceOp.SUM: 0,
    ReduceOp.AVG: 1,
    ReduceOp.MAX: 2,
    ReduceOp.MIN: 3,
}


def _dtype_code(dtype: np.dtype) -> int:
    global _DTYPE_CODES
    if not _DTYPE_CODES:
        import ml_dtypes

        _DTYPE_CODES = {
            np.dtype(np.float32): 0,
            np.dtype(np.float64): 1,
            np.dtype(np.int32): 2,
            np.dtype(np.int64): 3,
            np.dtype(np.uint8): 4,
            np.dtype(ml_dtypes.bfloat16): 5,
        }
    code = _DTYPE_CODES.get(np.dtype(dtype))
    if code is None:
        raise TypeError(f"unsupported dtype for native collectives: {dtype}")
    return code


def _configure_lib(lib: ctypes.CDLL) -> None:
    if getattr(lib, "_collective_configured", False):
        return
    lib.tpuft_collective_new.restype = ctypes.c_void_p
    lib.tpuft_collective_last_error.restype = ctypes.c_char_p
    lib.tpuft_collective_last_error.argtypes = [ctypes.c_void_p]
    lib.tpuft_collective_configure.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_shutdown.argtypes = [ctypes.c_void_p]
    lib.tpuft_collective_free.argtypes = [ctypes.c_void_p]
    lib.tpuft_collective_allreduce.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_allgather.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_broadcast.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_alltoall.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_send.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_recv.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
    ]
    lib.tpuft_collective_barrier.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib._collective_configured = True


def _ptr(array: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(array.ctypes.data)


class ProcessGroupNative(ProcessGroup):
    """Native-backend resizable PG (the NCCL/Gloo slot of the TPU stack)."""

    def __init__(self, timeout: float = 60.0) -> None:
        super().__init__()
        self._timeout = timeout
        self._lib = _native.load()
        _configure_lib(self._lib)
        self._handle: Optional[int] = None
        self._rank = 0
        self._world_size = 1
        self._errored_exc: Optional[Exception] = None
        self._ops: Optional["queue.Queue"] = None
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        fr.record(
            "pg_native", "configure", replica_id=replica_id, rank=rank,
            world_size=world_size,
        )
        self._teardown()
        self._errored_exc = None
        self._rank = rank
        self._world_size = world_size
        hostport, _, prefix = store_addr.partition("/")
        handle = self._lib.tpuft_collective_new()
        rc = self._lib.tpuft_collective_configure(
            handle,
            hostport.encode(),
            prefix.encode(),
            rank,
            world_size,
            int(self._timeout * 1000),
        )
        if rc != 0:
            err = self._lib.tpuft_collective_last_error(handle).decode()
            self._lib.tpuft_collective_free(handle)
            error = RuntimeError(f"native configure failed: {err}")
            self._errored_exc = error
            raise error
        self._handle = handle
        self._ops = queue.Queue()
        self._worker = threading.Thread(
            target=self._worker_loop, args=(self._ops,), daemon=True,
            name=f"native-pg-{replica_id}-{rank}",
        )
        self._worker.start()

    def _worker_loop(self, ops: "queue.Queue") -> None:
        while True:
            try:
                item = ops.get()
                if item is None:
                    return
                item()
            except BaseException as e:  # noqa: BLE001 — worker must survive
                # Ops capture their own exceptions into their Work future
                # (_submit); anything landing here is a bug in that capture,
                # and a dead worker would hang every later collective until
                # timeout — log and keep serving.
                logger.exception("native pg op-worker: op escaped its Work: %s", e)

    def _teardown(self) -> None:
        with self._lock:
            handle, self._handle = self._handle, None
            ops, self._ops = self._ops, None
        if handle is not None:
            # ::shutdown()s the sockets, failing any op blocked inside a C
            # call (fds stay allocated until the free below).
            self._lib.tpuft_collective_shutdown(handle)
        if ops is not None:
            ops.put(None)
        worker, self._worker = self._worker, None
        if worker is not None:
            worker.join(timeout=10.0)
        if handle is not None:
            if worker is not None and worker.is_alive():
                # The op thread is still inside the native call: freeing now
                # would be a use-after-free. Leak the handle (sockets are
                # already shut down, so the op will fail and the worker exit
                # eventually); better a bounded leak than a crash.
                logger.warning("native pg worker still running; leaking handle")
            else:
                self._lib.tpuft_collective_free(handle)

    def abort(self) -> None:
        self._errored_exc = self._errored_exc or RuntimeError("process group aborted")
        self._teardown()
        fr.dump_on_failure("pg_native", f"abort rank={self._rank}")

    def shutdown(self) -> None:
        self._teardown()

    def errored(self) -> Optional[Exception]:
        return self._errored_exc

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    # -- plumbing ----------------------------------------------------------

    def _last_error(self, handle: int) -> str:
        return self._lib.tpuft_collective_last_error(handle).decode()

    def _submit(self, fn: Callable[[int], object]) -> Work:
        if self._errored_exc is not None:
            raise RuntimeError(f"process group in error state: {self._errored_exc}")
        fut: Future = Future()
        op = fr.op_name_of(fn)
        fr.record("pg_native", "submit", op=op, rank=self._rank)
        # Read handle/queue and enqueue under the lock so a concurrent
        # _teardown cannot slip its None sentinel in between (which would
        # strand this op's future unresolved forever).
        with self._lock:
            handle, ops = self._handle, self._ops
            if handle is None or ops is None:
                raise RuntimeError("process group not configured")

            def run() -> None:
                start = time.monotonic()
                try:
                    fut.set_result(fn(handle))
                except BaseException as e:  # noqa: BLE001
                    if self._errored_exc is None:
                        self._errored_exc = (
                            e if isinstance(e, Exception) else RuntimeError(str(e))
                        )
                    # Resolve the waiter FIRST: a raising record() must not
                    # strand the future or kill the op-worker thread.
                    fut.set_exception(e)
                    fr.record("pg_native", "op_error", op=op, rank=self._rank, error=e)
                else:
                    fr.record(
                        "pg_native", "op_done", op=op, rank=self._rank,
                        ms=round(1e3 * (time.monotonic() - start), 2),
                    )

            ops.put(run)
        return Work(fut)

    def _check(self, rc: int, handle: int, op: str) -> None:
        if rc != 0:
            raise RuntimeError(f"native {op} failed: {self._last_error(handle)}")

    # -- collectives -------------------------------------------------------

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        timeout_ms = int(self._timeout * 1000)

        def run(handle: int) -> List[np.ndarray]:
            out = []
            for array in arrays:
                buf = array.copy()
                code = _dtype_code(buf.dtype)
                self._check(
                    self._lib.tpuft_collective_allreduce(
                        handle, _ptr(buf), buf.size, code, _REDUCE_CODES[op], timeout_ms
                    ),
                    handle,
                    "allreduce",
                )
                out.append(buf)
            return out

        return self._submit(run)

    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        # Variable shapes across ranks ride the generic send path: pack,
        # gather fixed-size length headers, then exchange payloads via the
        # equal-size alltoall... simplest correct: pickle + max-size pad.
        blob = pickle_dumps_arrays([np.asarray(a) for a in arrays])
        timeout_ms = int(self._timeout * 1000)

        def run(handle: int) -> List[List[np.ndarray]]:
            n = self._world_size
            length = np.array([len(blob)], dtype=np.int64)
            lengths = np.zeros(n, dtype=np.int64)
            self._check(
                self._lib.tpuft_collective_allgather(
                    handle, _ptr(length), _ptr(lengths), 1, _dtype_code(np.dtype(np.int64)), timeout_ms
                ),
                handle,
                "allgather",
            )
            max_len = int(lengths.max())
            padded = np.zeros(max_len, dtype=np.uint8)
            padded[: len(blob)] = np.frombuffer(blob, dtype=np.uint8)
            gathered = np.zeros(n * max_len, dtype=np.uint8)
            self._check(
                self._lib.tpuft_collective_allgather(
                    handle, _ptr(padded), _ptr(gathered), max_len,
                    _dtype_code(np.dtype(np.uint8)), timeout_ms,
                ),
                handle,
                "allgather",
            )
            return [
                pickle_loads_arrays(
                    gathered[r * max_len : r * max_len + int(lengths[r])].tobytes()
                )
                for r in range(n)
            ]

        return self._submit(run)

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        timeout_ms = int(self._timeout * 1000)

        def run(handle: int) -> List[np.ndarray]:
            out = []
            for array in arrays:
                buf = array.copy()
                self._check(
                    self._lib.tpuft_collective_broadcast(
                        handle, _ptr(buf), buf.size, _dtype_code(buf.dtype), root, timeout_ms
                    ),
                    handle,
                    "broadcast",
                )
                out.append(buf)
            return out

        return self._submit(run)

    def reduce_scatter(
        self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        reduced = self.allreduce(arrays, op)
        n = self._world_size
        rank = self._rank

        def split(result: List[np.ndarray]) -> List[np.ndarray]:
            out = []
            for a in result:
                if a.shape[0] % n != 0:
                    raise ValueError(
                        f"reduce_scatter requires dim0 ({a.shape[0]}) divisible by world_size ({n})"
                    )
                out.append(np.split(a, n, axis=0)[rank].copy())
            return out

        return reduced.then(split)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        if len(arrays) != self._world_size:
            raise ValueError(f"alltoall requires {self._world_size} arrays")
        shapes = {a.shape for a in arrays}
        dtypes = {a.dtype for a in arrays}
        if len(shapes) != 1 or len(dtypes) != 1:
            raise ValueError("native alltoall requires uniform shapes/dtypes")
        timeout_ms = int(self._timeout * 1000)

        def run(handle: int) -> List[np.ndarray]:
            stacked = np.concatenate([a.reshape(-1) for a in arrays])
            out = np.empty_like(stacked)
            per_rank = arrays[0].size
            self._check(
                self._lib.tpuft_collective_alltoall(
                    handle, _ptr(stacked), _ptr(out), per_rank,
                    _dtype_code(stacked.dtype), timeout_ms,
                ),
                handle,
                "alltoall",
            )
            return [
                out[r * per_rank : (r + 1) * per_rank].reshape(arrays[0].shape).copy()
                for r in range(self._world_size)
            ]

        return self._submit(run)

    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work:
        blob = pickle_dumps_arrays([np.asarray(a) for a in arrays])
        timeout_ms = int(self._timeout * 1000)

        def run(handle: int) -> None:
            header = np.array([len(blob)], dtype=np.int64)
            self._check(
                self._lib.tpuft_collective_send(handle, _ptr(header), 8, dst, timeout_ms),
                handle,
                "send",
            )
            payload = np.frombuffer(blob, dtype=np.uint8)
            self._check(
                self._lib.tpuft_collective_send(
                    handle, _ptr(payload), payload.size, dst, timeout_ms
                ),
                handle,
                "send",
            )

        return self._submit(run)

    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        timeout_ms = int(self._timeout * 1000)
        # In-place receive targets (PGTransport template fast path).
        targets = [a if isinstance(a, np.ndarray) else None for a in shapes_like]

        def run(handle: int) -> List[np.ndarray]:
            header = np.zeros(1, dtype=np.int64)
            self._check(
                self._lib.tpuft_collective_recv(handle, _ptr(header), 8, src, timeout_ms),
                handle,
                "recv",
            )
            payload = np.zeros(int(header[0]), dtype=np.uint8)
            self._check(
                self._lib.tpuft_collective_recv(
                    handle, _ptr(payload), payload.size, src, timeout_ms
                ),
                handle,
                "recv",
            )
            return pickle_loads_arrays(payload.tobytes(), out=targets)

        return self._submit(run)

    def barrier(self) -> Work:
        timeout_ms = int(self._timeout * 1000)

        def run(handle: int) -> None:
            self._check(
                self._lib.tpuft_collective_barrier(handle, timeout_ms), handle, "barrier"
            )

        return self._submit(run)
