"""Resizable process groups over host networking (the DCN comm layer).

The reference builds fault tolerance on reconfigurable wrappers of
NCCL/Gloo ProcessGroups (/root/reference/torchft/process_group.py:133-389):
``configure()`` tears the group down and re-rendezvouses under a fresh store
prefix, ``abort()`` cancels outstanding work, ``errored()`` reports a sticky
failure. On TPU the per-step gradient collective between replica *groups*
rides host networking (DCN) — intra-slice collectives are XLA's job inside
the jitted step — so the backend here is a TCP full-mesh between the
corresponding local ranks of each replica group, with the native store as
rendezvous.

Collectives operate on host numpy arrays (the manager stages jax arrays
device→host before averaging). bfloat16 is supported via ml_dtypes and
reduced in float32 for numerics.

Implementations:
  ProcessGroupTCP     — real sockets, full mesh, ring allreduce (Gloo role)
  ProcessGroupDummy   — world-size-1 loopback, op-counting (test/bootstrap)
  ErrorSwallowingProcessGroupWrapper — records first error, dummy-works after
  FakeProcessGroupWrapper — deterministic fault injection for tests
  ManagedProcessGroup — routes allreduce through a Manager (quorum semantics)
"""

from __future__ import annotations

import logging
import os
import pickle
import queue
import socket
import struct
import threading
import time
from abc import ABC, abstractmethod
from concurrent.futures import Future
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, cast

import numpy as np

from torchft_tpu._safe_pickle import safe_loads
from torchft_tpu.utils import netem

from torchft_tpu.parallel.store import StoreClient, create_store_client
from torchft_tpu.utils import flight_recorder as fr
from torchft_tpu.work import Work, _DummyWork

logger = logging.getLogger(__name__)

__all__ = [
    "ReduceOp",
    "ProcessGroup",
    "ProcessGroupTCP",
    "ProcessGroupDummy",
    "ErrorSwallowingProcessGroupWrapper",
    "FakeProcessGroupWrapper",
    "ManagedProcessGroup",
]


class ReduceOp(Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"


def _reduce_pair(acc: np.ndarray, other: np.ndarray, op: ReduceOp) -> np.ndarray:
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        return acc + other
    if op == ReduceOp.MAX:
        return np.maximum(acc, other)
    if op == ReduceOp.MIN:
        return np.minimum(acc, other)
    raise ValueError(f"unsupported reduce op {op}")


def _acc_dtype(dtype: np.dtype) -> np.dtype:
    """Accumulation dtype: low-precision floats reduce in float32."""
    if dtype.itemsize <= 2 and dtype.kind in ("f", "V"):  # fp16/bf16
        return np.dtype(np.float32)
    return dtype


class ProcessGroup(ABC):
    """Resizable collective group (reference: process_group.py:133-389).

    All collectives are asynchronous: they return a :class:`Work` whose
    ``wait()`` yields the result arrays. Implementations must make
    ``configure`` idempotent and safe to call while ops are outstanding
    (outstanding work fails, new epoch starts clean).
    """

    def __init__(self) -> None:
        self._timeout: float = 60.0

    # -- lifecycle ---------------------------------------------------------

    @abstractmethod
    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        """(Re)initializes the group: ``store_addr`` is "host:port/prefix",
        fresh per quorum; rank/world_size are the replica-axis coordinates."""

    @abstractmethod
    def abort(self) -> None:
        """Cancels outstanding collectives and poisons the group until the
        next configure()."""

    @abstractmethod
    def shutdown(self) -> None:
        """Permanently tears the group down."""

    @abstractmethod
    def errored(self) -> Optional[Exception]:
        """Sticky error state since last configure (None when healthy)."""

    def set_timeout(self, timeout: float) -> None:
        self._timeout = timeout

    @abstractmethod
    def size(self) -> int: ...

    @abstractmethod
    def rank(self) -> int: ...

    def getBackendName(self) -> str:
        return type(self).__name__

    # -- collectives -------------------------------------------------------

    @abstractmethod
    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        """Elementwise reduction of each array across ranks; result on all."""

    @abstractmethod
    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        """Result: list over ranks of the rank's array list."""

    @abstractmethod
    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        """Root's arrays distributed to all ranks."""

    @abstractmethod
    def reduce_scatter(
        self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        """Reduce then scatter: each array is split into world_size equal
        chunks along axis 0; rank r receives reduced chunk r."""

    @abstractmethod
    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        """arrays[i] goes to rank i; result[i] came from rank i."""

    @abstractmethod
    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work: ...

    @abstractmethod
    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        """Receives arrays matching ``shapes_like`` (shape/dtype templates)."""

    @abstractmethod
    def barrier(self) -> Work: ...


# ---------------------------------------------------------------------------
# TCP backend
# ---------------------------------------------------------------------------

_LEN_STRUCT = struct.Struct("!Q")

# Arrays at or above this take the ring allreduce (bandwidth-optimal);
# smaller ones take gather-at-root (latency-optimal). Override in MB.
_RING_MIN_BYTES = int(
    float(os.environ.get("TPUFT_TCP_RING_MIN_MB", "1")) * 1024 * 1024
)


def _send_bytes(sock: socket.socket, payload: bytes, deadline: float) -> None:
    # No-op unless an emulated-DCN link is set; deadline-bounded so the
    # emulated link times the op out exactly where a real link would.
    netem.pace_deadline(len(payload), deadline)
    sock.settimeout(max(0.001, deadline - time.monotonic()))
    sock.sendall(_LEN_STRUCT.pack(len(payload)) + payload)


def _recv_bytes(sock: socket.socket, deadline: float) -> bytes:
    header = _recv_exact(sock, _LEN_STRUCT.size, deadline)
    (length,) = _LEN_STRUCT.unpack(header)
    return _recv_exact(sock, length, deadline)


def _recv_exact(sock: socket.socket, n: int, deadline: float) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        sock.settimeout(max(0.001, deadline - time.monotonic()))
        chunk = sock.recv_into(view[got:], n - got)
        if chunk == 0:
            raise ConnectionError("peer closed connection")
        got += chunk
    return bytes(buf)


def _pack_array(array: np.ndarray) -> bytes:
    array = np.ascontiguousarray(array)
    meta = pickle.dumps((array.shape, array.dtype.str if array.dtype.names is None else None, str(array.dtype)))
    return _LEN_STRUCT.pack(len(meta)) + meta + array.tobytes()


def _unpack_array(payload: bytes, out: Optional[np.ndarray] = None) -> np.ndarray:
    (meta_len,) = _LEN_STRUCT.unpack_from(payload)
    meta = safe_loads(payload[_LEN_STRUCT.size : _LEN_STRUCT.size + meta_len])
    shape, _, dtype_name = meta
    # ml_dtypes names (e.g. bfloat16) resolve through the registry.
    try:
        dtype = np.dtype(dtype_name)
    except TypeError:
        import ml_dtypes

        dtype = np.dtype(getattr(ml_dtypes, dtype_name))
    data = payload[_LEN_STRUCT.size + meta_len :]
    view = np.frombuffer(data, dtype=dtype).reshape(shape)
    if (
        out is not None
        and tuple(out.shape) == tuple(shape)
        and out.dtype == dtype
        and out.flags.writeable
    ):
        # In-place receive: decode into the caller's existing storage (the
        # PGTransport template fast path — no result allocation).
        np.copyto(out, view)
        return out
    return view.copy()


class _Epoch:
    """One configure() generation of a ProcessGroupTCP: the listener, the
    full mesh of peer sockets, and the worker that executes collectives."""

    def __init__(
        self,
        pg_name: str,
        store: StoreClient,
        rank: int,
        world_size: int,
        timeout: float,
    ) -> None:
        self.rank = rank
        self.world_size = world_size
        self.closed = False
        self._lock = threading.Lock()
        self.peers: Dict[int, socket.socket] = {}
        self._listener: Optional[socket.socket] = None
        deadline = time.monotonic() + timeout

        if world_size > 1:
            listener = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(("::", 0))
            listener.listen(world_size)
            self._listener = listener
            port = listener.getsockname()[1]
            host = socket.gethostname()
            store.set(f"ep/{rank}", f"{host}:{port}".encode())

            # Deterministic mesh setup: rank r dials every lower rank and
            # accepts one inbound connection from every higher rank.
            pending = world_size - 1 - rank
            accepted: Dict[int, socket.socket] = {}
            accept_err: List[Exception] = []

            def accept_loop() -> None:
                try:
                    for _ in range(pending):
                        listener.settimeout(max(0.001, deadline - time.monotonic()))
                        conn, _ = listener.accept()
                        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                        peer_rank = struct.unpack("!I", _recv_exact(conn, 4, deadline))[0]
                        accepted[peer_rank] = conn
                except Exception as e:  # noqa: BLE001
                    accept_err.append(e)

            acceptor = threading.Thread(target=accept_loop, daemon=True, name=f"{pg_name}-accept")
            acceptor.start()

            for peer in range(rank):
                addr = store.get(f"ep/{peer}", timeout=max(0.001, deadline - time.monotonic()))
                assert addr is not None
                peer_host, _, peer_port = addr.decode().rpartition(":")
                sock = socket.create_connection(
                    (peer_host, int(peer_port)),
                    timeout=max(0.001, deadline - time.monotonic()),
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.sendall(struct.pack("!I", rank))
                self.peers[peer] = sock

            acceptor.join(timeout=max(0.001, deadline - time.monotonic()))
            if acceptor.is_alive() or accept_err:
                self.close()
                raise TimeoutError(
                    f"rendezvous failed for rank {rank}/{world_size}: "
                    f"{accept_err[0] if accept_err else 'accept timeout'}"
                )
            self.peers.update(accepted)

        # Collectives execute in submission order on a dedicated worker so the
        # train loop can overlap compute with communication.
        self.ops: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self.worker = threading.Thread(
            target=self._worker_loop, daemon=True, name=f"{pg_name}-worker"
        )
        self.worker.start()

    def _worker_loop(self) -> None:
        while True:
            try:
                op = self.ops.get()
                if op is None:
                    return
                op()
            except BaseException as e:  # noqa: BLE001 — worker must survive
                # Submitted ops capture their own exceptions into their
                # Future (see submit); anything landing here is a bug in
                # that capture — a dead worker would silently hang every
                # later collective until its timeout, so log and keep
                # serving (the op's Future still times out and reports).
                logger.exception("pg op-worker: op escaped its Future: %s", e)

    def submit(self, fn: Callable[[], object]) -> Future:
        fut: Future = Future()

        def run() -> None:
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        self.ops.put(run)
        return fut

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
        if hasattr(self, "ops"):
            self.ops.put(None)
        for sock in self.peers.values():
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


class ProcessGroupTCP(ProcessGroup):
    """Gloo-role backend: full TCP mesh between the same local rank of each
    replica group. Reductions run in rank-ascending order at a root and the
    result is broadcast, so all replicas produce bitwise-identical output —
    the invariant the recovery tests assert.
    """

    def __init__(self, timeout: float = 60.0) -> None:
        super().__init__()
        self._timeout = timeout
        self._epoch: Optional[_Epoch] = None
        self._errored: Optional[Exception] = None
        self._rank = 0
        self._world_size = 1
        self._configure_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        fr.record(
            "pg_tcp", "configure", replica_id=replica_id, rank=rank,
            world_size=world_size,
        )
        with self._configure_lock:
            old = self._epoch
            self._epoch = None
            if old is not None:
                old.close()
            self._errored = None
            self._rank = rank
            self._world_size = world_size
            store = create_store_client(store_addr, connect_timeout=self._timeout)
            try:
                self._epoch = _Epoch(
                    f"pg-{replica_id}-{rank}", store, rank, world_size, self._timeout
                )
            except Exception as e:
                self._errored = e
                raise
            finally:
                store.close()

    def abort(self) -> None:
        self._errored = self._errored or RuntimeError("process group aborted")
        epoch = self._epoch
        if epoch is not None:
            logger.warning("process_group_abort rank=%d", self._rank)
            epoch.close()
        fr.dump_on_failure("pg_tcp", f"abort rank={self._rank}")

    def shutdown(self) -> None:
        epoch = self._epoch
        self._epoch = None
        if epoch is not None:
            epoch.close()

    def errored(self) -> Optional[Exception]:
        return self._errored

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    # -- plumbing ----------------------------------------------------------

    def _submit(self, fn: Callable[["_Epoch", float], object]) -> Work:
        if self._errored is not None:
            raise RuntimeError(f"process group in error state: {self._errored}")
        epoch = self._epoch
        if epoch is None:
            raise RuntimeError("process group not configured")
        deadline = time.monotonic() + self._timeout
        op = fr.op_name_of(fn)
        fr.record("pg_tcp", "submit", op=op, rank=self._rank)

        def run() -> object:
            start = time.monotonic()
            try:
                result = fn(epoch, deadline)
            except BaseException as e:
                # First failure poisons the group until reconfigure.
                if self._errored is None:
                    self._errored = e if isinstance(e, Exception) else RuntimeError(str(e))
                epoch.close()
                fr.record("pg_tcp", "op_error", op=op, rank=self._rank, error=e)
                raise
            fr.record(
                "pg_tcp", "op_done", op=op, rank=self._rank,
                ms=round(1e3 * (time.monotonic() - start), 2),
            )
            return result

        return Work(epoch.submit(run))

    def _sendto(self, epoch: _Epoch, peer: int, payload: bytes, deadline: float) -> None:
        _send_bytes(epoch.peers[peer], payload, deadline)

    def _recvfrom(self, epoch: _Epoch, peer: int, deadline: float) -> bytes:
        return _recv_bytes(epoch.peers[peer], deadline)

    # -- collectives -------------------------------------------------------

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        arrays = [np.asarray(a) for a in arrays]

        def run(epoch: _Epoch, deadline: float) -> List[np.ndarray]:
            return self._allreduce_sync(epoch, arrays, op, deadline)

        return self._submit(run)

    def _allreduce_sync(
        self,
        epoch: _Epoch,
        arrays: List[np.ndarray],
        op: ReduceOp,
        deadline: float,
    ) -> List[np.ndarray]:
        n = epoch.world_size
        if n == 1:
            return [a.copy() for a in arrays]
        # Large payloads take the bandwidth-optimal ring (each rank moves
        # ~2x payload regardless of N); small ones take gather-at-root +
        # broadcast, whose single reduction order is the simplest
        # determinism argument and whose latency (2 hops vs 2(N-1) steps)
        # wins when payloads are tiny. Both end bitwise identical on every
        # rank. SUM/AVG only on the ring (MAX/MIN payloads are small in
        # practice and keep the root path).
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            small: List[int] = []
            out_mixed: List[Optional[np.ndarray]] = [None] * len(arrays)
            for i, a in enumerate(arrays):
                if a.nbytes >= _RING_MIN_BYTES:
                    out_mixed[i] = self._ring_allreduce(epoch, a, op, deadline)
                else:
                    small.append(i)
            if not small:
                return [cast(np.ndarray, x) for x in out_mixed]
            if len(small) < len(arrays):
                reduced_small = self._allreduce_root(
                    epoch, [arrays[i] for i in small], op, deadline
                )
                for slot, i in enumerate(small):
                    out_mixed[i] = reduced_small[slot]
                return [cast(np.ndarray, x) for x in out_mixed]
        return self._allreduce_root(epoch, arrays, op, deadline)

    def _ring_allreduce(
        self, epoch: _Epoch, array: np.ndarray, op: ReduceOp, deadline: float
    ) -> np.ndarray:
        """Ring reduce-scatter + allgather over the full-mesh sockets. Each
        chunk has exactly one accumulation order (ring order starting at its
        owner), so every rank ends with identical bytes."""
        n = epoch.world_size
        rank = epoch.rank
        next_peer = (rank + 1) % n
        prev_peer = (rank - 1) % n
        acc_dtype = _acc_dtype(array.dtype)
        flat = array.reshape(-1).astype(acc_dtype, copy=True)
        bounds = np.linspace(0, flat.size, n + 1, dtype=np.int64)

        def chunk(index: int) -> np.ndarray:
            index %= n
            return flat[bounds[index] : bounds[index + 1]]

        def exchange(send_buf: bytes) -> bytes:
            # Full-duplex: send on a helper thread while receiving, or two
            # big sendalls would deadlock on socket buffers.
            error: List[BaseException] = []

            def do_send() -> None:
                try:
                    _send_bytes(epoch.peers[next_peer], send_buf, deadline)
                except BaseException as e:  # noqa: BLE001
                    error.append(e)

            sender = threading.Thread(target=do_send)
            sender.start()
            received = _recv_bytes(epoch.peers[prev_peer], deadline)
            sender.join()
            if error:
                raise error[0]
            return received

        # Phase 1 - reduce-scatter: after n-1 steps, rank owns the fully
        # reduced chunk (rank+1).
        for step in range(n - 1):
            send_chunk = chunk(rank - step)
            received = exchange(send_chunk.tobytes())
            target = chunk(rank - step - 1)
            target += np.frombuffer(received, dtype=acc_dtype)
        own = rank + 1
        if op == ReduceOp.AVG:
            chunk(own)[...] = chunk(own) / n
        # Phase 2 - allgather: circulate reduced chunks around the ring in
        # the ORIGINAL dtype — each owner downcasts its chunk exactly once,
        # so bf16 payloads move 2 bytes/element (not the f32 accumulator's
        # 4) and every rank still ends bitwise identical.
        out = np.empty(flat.size, dtype=array.dtype)

        def out_chunk(index: int) -> np.ndarray:
            index %= n
            return out[bounds[index] : bounds[index + 1]]

        out_chunk(own)[...] = chunk(own).astype(array.dtype)
        for step in range(n - 1):
            send_chunk = out_chunk(own - step)
            received = exchange(np.ascontiguousarray(send_chunk).tobytes())
            out_chunk(own - step - 1)[...] = np.frombuffer(
                received, dtype=array.dtype
            )
        return out.reshape(array.shape)

    def _allreduce_root(
        self,
        epoch: _Epoch,
        arrays: List[np.ndarray],
        op: ReduceOp,
        deadline: float,
    ) -> List[np.ndarray]:
        n = epoch.world_size
        # Gather-at-root with rank-ascending reduction, broadcast result: all
        # ranks end bitwise identical.
        rank = epoch.rank
        out: List[np.ndarray] = []
        if rank == 0:
            gathered: Dict[int, List[np.ndarray]] = {0: arrays}
            for peer in range(1, n):
                payload = self._recvfrom(epoch, peer, deadline)
                gathered[peer] = pickle_loads_arrays(payload)
            for i, a in enumerate(arrays):
                acc = gathered[0][i].astype(_acc_dtype(a.dtype), copy=True)
                for peer in range(1, n):
                    acc = _reduce_pair(acc, gathered[peer][i].astype(_acc_dtype(a.dtype)), op)
                if op == ReduceOp.AVG:
                    acc = acc / n
                out.append(acc.astype(a.dtype))
            blob = pickle_dumps_arrays(out)
            for peer in range(1, n):
                self._sendto(epoch, peer, blob, deadline)
        else:
            self._sendto(epoch, 0, pickle_dumps_arrays(arrays), deadline)
            out = pickle_loads_arrays(self._recvfrom(epoch, 0, deadline))
        return out

    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        arrays = [np.asarray(a) for a in arrays]

        def run(epoch: _Epoch, deadline: float) -> List[List[np.ndarray]]:
            n = epoch.world_size
            if n == 1:
                return [[a.copy() for a in arrays]]
            rank = epoch.rank
            if rank == 0:
                result: List[List[np.ndarray]] = [list(arrays)]
                for peer in range(1, n):
                    result.append(pickle_loads_arrays(self._recvfrom(epoch, peer, deadline)))
                blob = pickle.dumps([pickle_dumps_arrays(r) for r in result])
                for peer in range(1, n):
                    self._sendto(epoch, peer, blob, deadline)
                return result
            self._sendto(epoch, 0, pickle_dumps_arrays(arrays), deadline)
            blobs = safe_loads(self._recvfrom(epoch, 0, deadline))
            return [pickle_loads_arrays(b) for b in blobs]

        return self._submit(run)

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        arrays = [np.asarray(a) for a in arrays]

        def run(epoch: _Epoch, deadline: float) -> List[np.ndarray]:
            n = epoch.world_size
            if n == 1:
                return [a.copy() for a in arrays]
            rank = epoch.rank
            if rank == root:
                blob = pickle_dumps_arrays(arrays)
                for peer in range(n):
                    if peer != root:
                        self._sendto(epoch, peer, blob, deadline)
                return [a.copy() for a in arrays]
            return pickle_loads_arrays(self._recvfrom(epoch, root, deadline))

        return self._submit(run)

    def reduce_scatter(
        self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        arrays = [np.asarray(a) for a in arrays]

        def run(epoch: _Epoch, deadline: float) -> List[np.ndarray]:
            n = epoch.world_size
            reduced = self._allreduce_sync(epoch, list(arrays), op, deadline)
            out = []
            for a in reduced:
                if a.shape[0] % n != 0:
                    raise ValueError(
                        f"reduce_scatter requires dim0 ({a.shape[0]}) divisible by world_size ({n})"
                    )
                out.append(np.split(a, n, axis=0)[epoch.rank].copy())
            return out

        return self._submit(run)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        arrays = [np.asarray(a) for a in arrays]

        def run(epoch: _Epoch, deadline: float) -> List[np.ndarray]:
            n = epoch.world_size
            if len(arrays) != n:
                raise ValueError(f"alltoall requires {n} arrays, got {len(arrays)}")
            rank = epoch.rank
            result: List[Optional[np.ndarray]] = [None] * n
            result[rank] = arrays[rank].copy()
            # Pairwise exchange ordered to avoid deadlock: lower rank sends
            # first in each pair.
            for peer in range(n):
                if peer == rank:
                    continue
                if rank < peer:
                    self._sendto(epoch, peer, _pack_array(arrays[peer]), deadline)
                    result[peer] = _unpack_array(self._recvfrom(epoch, peer, deadline))
                else:
                    result[peer] = _unpack_array(self._recvfrom(epoch, peer, deadline))
                    self._sendto(epoch, peer, _pack_array(arrays[peer]), deadline)
            return result  # type: ignore[return-value]

        return self._submit(run)

    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work:
        arrays = [np.asarray(a) for a in arrays]

        def run(epoch: _Epoch, deadline: float) -> None:
            self._sendto(epoch, dst, pickle_dumps_arrays(arrays), deadline)

        return self._submit(run)

    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        targets = [a if isinstance(a, np.ndarray) else None for a in shapes_like]

        def run(epoch: _Epoch, deadline: float) -> List[np.ndarray]:
            return pickle_loads_arrays(
                self._recvfrom(epoch, src, deadline), out=targets
            )

        return self._submit(run)

    def barrier(self) -> Work:
        return self.allreduce([np.zeros(1, dtype=np.float32)])


def pickle_dumps_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    parts = [struct.pack("!I", len(arrays))]
    for a in arrays:
        packed = _pack_array(a)
        parts.append(_LEN_STRUCT.pack(len(packed)))
        parts.append(packed)
    return b"".join(parts)


def pickle_loads_arrays(
    payload: bytes, out: Optional[Sequence[np.ndarray]] = None
) -> List[np.ndarray]:
    (count,) = struct.unpack_from("!I", payload)
    offset = 4
    result = []
    for index in range(count):
        (length,) = _LEN_STRUCT.unpack_from(payload, offset)
        offset += _LEN_STRUCT.size
        target = out[index] if out is not None and index < len(out) else None
        result.append(_unpack_array(payload[offset : offset + length], out=target))
        offset += length
    return result


# ---------------------------------------------------------------------------
# Loopback / wrappers
# ---------------------------------------------------------------------------


class ProcessGroupDummy(ProcessGroup):
    """World-size-1 loopback: copies inputs to outputs, counts calls
    (reference: process_group.py:960-1081). Soaks up bootstrap collectives
    and backs tests."""

    def __init__(self, rank: int = 0, world: int = 1) -> None:
        super().__init__()
        assert rank == 0 and world == 1
        self._rank = rank
        self._world = world
        self.configure_count = 0
        self.op_counts: Dict[str, int] = {}
        self._errored: Optional[Exception] = None

    def _count(self, name: str) -> None:
        self.op_counts[name] = self.op_counts.get(name, 0) + 1

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self.configure_count += 1

    def abort(self) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def errored(self) -> Optional[Exception]:
        return self._errored

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        self._count("allreduce")
        return _DummyWork([np.array(a) for a in arrays])

    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        self._count("allgather")
        return _DummyWork([[np.array(a) for a in arrays]])

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        self._count("broadcast")
        return _DummyWork([np.array(a) for a in arrays])

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        self._count("reduce_scatter")
        return _DummyWork([np.array(a) for a in arrays])

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        self._count("alltoall")
        return _DummyWork([np.array(a) for a in arrays])

    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work:
        self._count("send")
        return _DummyWork(None)

    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        self._count("recv")
        return _DummyWork([np.array(a) for a in shapes_like])

    def barrier(self) -> Work:
        self._count("barrier")
        return _DummyWork(None)


class _WrapperBase(ProcessGroup):
    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__()
        self._pg = pg

    @property
    def parent(self) -> ProcessGroup:
        return self._pg

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self._pg.configure(store_addr, replica_id, rank, world_size)

    def abort(self) -> None:
        self._pg.abort()

    def shutdown(self) -> None:
        self._pg.shutdown()

    def errored(self) -> Optional[Exception]:
        return self._pg.errored()

    def set_timeout(self, timeout: float) -> None:
        self._pg.set_timeout(timeout)

    def size(self) -> int:
        return self._pg.size()

    def rank(self) -> int:
        return self._pg.rank()

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._pg.allreduce(arrays, op)

    def allgather(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._pg.allgather(arrays)

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        return self._pg.broadcast(arrays, root)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._pg.reduce_scatter(arrays, op)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._pg.alltoall(arrays)

    def send(self, arrays: Sequence[np.ndarray], dst: int, tag: int = 0) -> Work:
        return self._pg.send(arrays, dst, tag)

    def recv(self, shapes_like: Sequence[np.ndarray], src: int, tag: int = 0) -> Work:
        return self._pg.recv(shapes_like, src, tag)

    def barrier(self) -> Work:
        return self._pg.barrier()


class ErrorSwallowingProcessGroupWrapper(_WrapperBase):
    """Converts collective exceptions into a recorded error + dummy work;
    everything after the first error is skipped until reconfigure (reference:
    process_group.py:1084-1179). Lets the train loop keep stepping while the
    manager arranges reconfiguration."""

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__(pg)
        self._error: Optional[Exception] = None

    def errored(self) -> Optional[Exception]:
        return self._error or self._pg.errored()

    def report_error(self, e: Exception) -> None:
        self._error = e

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self._error = None
        super().configure(store_addr, replica_id, rank, world_size)

    def _guard(self, fn: Callable[[], Work], fallback: object) -> Work:
        if self.errored() is not None:
            return _DummyWork(fallback)
        try:
            work = fn()
        except Exception as e:  # noqa: BLE001
            self.report_error(e)
            return _DummyWork(fallback)
        return work.with_error_handler(self.report_error, fallback)

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._guard(
            lambda: self._pg.allreduce(arrays, op), [np.array(a) for a in arrays]
        )

    def broadcast(self, arrays: Sequence[np.ndarray], root: int = 0) -> Work:
        return self._guard(
            lambda: self._pg.broadcast(arrays, root), [np.array(a) for a in arrays]
        )


class FakeProcessGroupWrapper(_WrapperBase):
    """Test-only fault injection (reference: process_group.py:1182-1230):
    ``report_future_error`` poisons the next collective's result."""

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__(pg)
        self._next_error: Optional[Exception] = None
        self._injected: Optional[Exception] = None

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self._injected = None
        super().configure(store_addr, replica_id, rank, world_size)

    def report_future_error(self, e: Exception) -> None:
        self._next_error = e

    def errored(self) -> Optional[Exception]:
        return self._injected or super().errored()

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        work = self._pg.allreduce(arrays, op)
        if self._next_error is not None:
            error, self._next_error = self._next_error, None
            self._injected = error
            return Work.failed(error)
        return work


class ManagedProcessGroup(_WrapperBase):
    """Routes allreduce through the Manager so it picks up quorum/error
    semantics; size() reports the live participant count (reference:
    process_group.py:1233-1266). This is how mesh-based code transparently
    uses the fault-tolerant path."""

    def __init__(self, manager: "Manager") -> None:  # noqa: F821
        super().__init__(manager._pg)
        self._manager = manager

    def allreduce(self, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.AVG) -> Work:
        # Default is AVG (gradient averaging), matching the reference's
        # AVG-only ManagedProcessGroup (process_group.py:1251-1263). Only
        # SUM/AVG have world-size-independent manager semantics (SUM +
        # divide-by-participants); MAX/MIN would silently change meaning when
        # non-participants contribute zeros, so reject them loudly.
        if op not in (ReduceOp.SUM, ReduceOp.AVG):
            raise ValueError(
                f"ManagedProcessGroup.allreduce supports SUM/AVG only, got {op}"
            )
        if op == ReduceOp.AVG:
            # One bucketed wire collective for the whole list (a list is a
            # pytree) instead of one collective per array.
            return self._manager.allreduce_pytree(list(arrays))
        return Work.gather(
            [self._manager.allreduce(array, reduce_op=op) for array in arrays]
        )

    def size(self) -> int:
        return self._manager.num_participants()

    def getBackendName(self) -> str:
        return "tpuft-managed"
