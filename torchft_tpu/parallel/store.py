"""Rendezvous store: the config/coordination KV plane.

Fills the role torch's TCPStore plays in the reference (one per replica
group, prefixed per quorum: /root/reference/torchft/process_group.py:111-130,
manager.py:319-325, :670-674). The server is native C++ (native/src/store.cc)
embedded via ctypes; clients speak the framed protocol.

Address convention (mirrors the reference's ``create_store_client``):
``"host:port/prefix"`` — the prefix namespaces keys so each quorum round gets
a fresh keyspace on the same server.
"""

from __future__ import annotations

import ctypes
from typing import Optional

from torchft_tpu import _native
from torchft_tpu.coordination import _FramedClient
from torchft_tpu.proto import tpuft_pb2

__all__ = ["StoreServer", "StoreClient", "create_store_client"]

_STORE_SET = 32
_STORE_GET = 33
_STORE_ADD = 34
_STORE_DELETE = 35


class StoreServer:
    """Embedded native KV store server."""

    def __init__(self, bind: str = "[::]:0") -> None:
        lib = _native.load()
        self._lib = lib
        self._handle = lib.tpuft_store_new(bind.encode())
        if not self._handle:
            raise RuntimeError(f"failed to start store: {_native.last_error()}")

    def address(self) -> str:
        buf = ctypes.create_string_buffer(512)
        self._lib.tpuft_store_address(self._handle, buf, len(buf))
        return buf.value.decode()

    def shutdown(self) -> None:
        if self._handle:
            self._lib.tpuft_store_shutdown(self._handle)
            self._lib.tpuft_store_free(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass


class StoreClient:
    """KV client with optional key prefix. Thread-compatible per instance."""

    def __init__(self, addr: str, prefix: str = "", connect_timeout: float = 10.0) -> None:
        self._client = _FramedClient(addr, connect_timeout)
        self._prefix = prefix.rstrip("/")

    def _key(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def set(self, key: str, value: bytes, timeout: float = 10.0) -> None:
        req = tpuft_pb2.StoreSetRequest(key=self._key(key), value=value)
        self._client.call(_STORE_SET, req.SerializeToString(), timeout)

    def get(self, key: str, timeout: float = 60.0, wait: bool = True) -> Optional[bytes]:
        """Returns the value; blocks until set when ``wait``. None if absent
        and not waiting; raises TimeoutError on wait timeout."""
        req = tpuft_pb2.StoreGetRequest(
            key=self._key(key), wait=wait, timeout_ms=int(timeout * 1000)
        )
        body = self._client.call(_STORE_GET, req.SerializeToString(), timeout + 5.0)
        resp = tpuft_pb2.StoreGetResponse()
        resp.ParseFromString(body)
        return resp.value if resp.found else None

    def add(self, key: str, delta: int = 1, timeout: float = 10.0) -> int:
        """Atomically adds ``delta`` to a counter; returns the new value."""
        req = tpuft_pb2.StoreAddRequest(key=self._key(key), delta=delta)
        body = self._client.call(_STORE_ADD, req.SerializeToString(), timeout)
        resp = tpuft_pb2.StoreAddResponse()
        resp.ParseFromString(body)
        return resp.value

    def delete(self, key: str, timeout: float = 10.0) -> bool:
        req = tpuft_pb2.StoreDeleteRequest(key=self._key(key))
        body = self._client.call(_STORE_DELETE, req.SerializeToString(), timeout)
        resp = tpuft_pb2.StoreDeleteResponse()
        resp.ParseFromString(body)
        return resp.deleted

    def sub_store(self, prefix: str) -> "StoreClient":
        """A new client sharing the server but nesting the key prefix."""
        sub = StoreClient.__new__(StoreClient)
        sub._client = _FramedClient(self._client.addr, self._client._connect_timeout)
        sub._prefix = self._key(prefix)
        return sub

    def close(self) -> None:
        self._client.close()


def create_store_client(store_addr: str, connect_timeout: float = 10.0) -> StoreClient:
    """Parses ``"host:port/prefix"`` into a prefixed client (reference:
    process_group.py:111-130)."""
    if "/" in store_addr:
        hostport, _, prefix = store_addr.partition("/")
    else:
        hostport, prefix = store_addr, ""
    return StoreClient(hostport, prefix, connect_timeout)
