"""Fault-tolerant parameter server prototype.

Role-equivalent of the reference's ``torchft/parameter_server.py:31-195``:
a lighthouse-free pattern built directly on reconfigurable process groups.
The server exposes an HTTP ``/new_session`` endpoint that hands out a fresh
store prefix + session id; each session gets its own 2-rank process group
(server rank 0, client rank 1) serviced by a handler thread running the
user's :meth:`forward`. Because every session has an isolated PG, a dead or
wedged client only costs its own session.
"""

from __future__ import annotations

import functools
import json
import os
import socket
import threading
import urllib.request
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict

from torchft_tpu.parallel.process_group import ProcessGroup, ProcessGroupTCP
from torchft_tpu.parallel.store import StoreServer
from torchft_tpu.telemetry import errors_logger

__all__ = ["ParameterServer"]


class ParameterServer(ABC):
    """Subclass and implement :meth:`forward`; run one per serving host.

    Example::

        class EchoPS(ParameterServer):
            def forward(self, session_id, pg):
                (req,) = pg.recv([np.empty(4)], src=1).wait(self.timeout)
                pg.send([req * 2], dst=1).wait(self.timeout)
    """

    def __init__(self, bind_port: int = 0, timeout: float = 60.0) -> None:
        self.timeout = timeout
        self._store = StoreServer()
        # Live session service threads, so shutdown can bound-join them
        # instead of abandoning daemon threads mid-RPC.
        self._sessions_lock = threading.Lock()
        self._sessions: Dict[str, threading.Thread] = {}
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:
                pass

            def do_POST(self) -> None:
                if self.path != "/new_session":
                    self.send_error(404)
                    return
                session_id = str(uuid.uuid4())
                body = json.dumps(
                    {
                        "session_id": session_id,
                        "store_addr": f"{server._store.address()}/session/{session_id}",
                    }
                ).encode()
                # Service thread joins the session PG as rank 0.
                thread = threading.Thread(
                    target=server._serve_session,
                    args=(session_id,),
                    daemon=True,
                    name=f"ps-session-{session_id[:8]}",
                )
                with server._sessions_lock:
                    server._sessions[session_id] = thread
                thread.start()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class DualStack(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._http = DualStack(("::", bind_port), Handler)
        self._http_thread = threading.Thread(
            target=functools.partial(self._http.serve_forever, poll_interval=0.05), daemon=True, name="tpuft-ps-http"
        )
        self._http_thread.start()

    def address(self) -> str:
        return f"http://{socket.gethostname()}:{self._http.server_address[1]}"

    def _serve_session(self, session_id: str) -> None:
        pg = ProcessGroupTCP(timeout=self.timeout)
        try:
            pg.configure(
                f"{self._store.address()}/session/{session_id}",
                f"ps-server-{session_id}",
                rank=0,
                world_size=2,
            )
            self.forward(session_id, pg)
        except Exception as e:  # noqa: BLE001  — a broken session only kills itself
            # Containment is the contract, silence is not: a wedged or
            # crashed session must be diagnosable by its id from the
            # telemetry stream (the reference pattern — errors narrate,
            # they never escape the session boundary).
            errors_logger.error(
                "parameter-server session failed",
                extra={
                    "job_id": os.environ.get("JOB_ID", "unknown"),
                    "replica_id": f"ps-session-{session_id}",
                    "error": f"{type(e).__name__}: {e}",
                },
                exc_info=True,
            )
        finally:
            pg.shutdown()
            with self._sessions_lock:
                self._sessions.pop(session_id, None)

    @abstractmethod
    def forward(self, session_id: str, pg: ProcessGroup) -> None:
        """Services one client session over its dedicated 2-rank group."""

    @classmethod
    def connect(cls, address: str, timeout: float = 60.0) -> ProcessGroup:
        """Client side: requests a session and joins its PG as rank 1."""
        req = urllib.request.Request(f"{address}/new_session", method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            session = json.loads(resp.read())
        pg = ProcessGroupTCP(timeout=timeout)
        pg.configure(
            session["store_addr"],
            f"ps-client-{session['session_id']}",
            rank=1,
            world_size=2,
        )
        return pg

    def shutdown(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self._store.shutdown()
        # Bound-join live session threads: the store shutdown above
        # unblocks their PG waits, so each join is short — a session that
        # outlives its slice is left to its daemon flag, not waited on
        # forever.
        with self._sessions_lock:
            threads = list(self._sessions.values())
        for thread in threads:
            thread.join(timeout=self.timeout)
