"""Chaos tool: inject faults into replica groups of a live job.

Role-equivalent of the reference's ``examples/slurm/punisher.py`` kill CLI
plus the monarch failure menu (examples/monarch/utils/failure.py:25-100):
resolves the current quorum from the lighthouse and fires fault RPCs at
member managers. Modes: exit (process death), segfault (crash with core),
deadlock (coordination wedges while heartbeats continue), partition
(heartbeats + RPC serving stop).

    python -m torchft_tpu.punisher --lighthouse host:29510 kill_one
    python -m torchft_tpu.punisher --lighthouse host:29510 fault_one --mode deadlock
    python -m torchft_tpu.punisher --lighthouse host:29510 kill_loop --mtbf 60 \
        --menu exit,segfault,deadlock,partition
"""

from __future__ import annotations

import argparse
import os
import random
import time

from torchft_tpu.coordination import LighthouseClient

__all__ = ["kill_one", "kill_all", "kill_loop", "main"]


def _members(client: LighthouseClient):
    status = client.status()
    return [m.member.replica_id for m in status.members if not m.joining]


FAULT_MODES = ("exit", "segfault", "deadlock", "partition")


def kill_one(
    client: LighthouseClient, rng: random.Random, mode: str = "exit"
) -> None:
    members = _members(client)
    if not members:
        print("[punisher] no quorum members to kill")
        return
    victim = rng.choice(members)
    print(f"[punisher] injecting {mode} into {victim}")
    try:
        client.kill(victim, mode=mode)
    except Exception as e:  # noqa: BLE001  — victim may die before replying
        print(f"[punisher] kill rpc ended with: {e}")


def kill_all(client: LighthouseClient, rng: random.Random) -> None:
    for victim in _members(client):
        print(f"[punisher] killing {victim}")
        try:
            client.kill(victim)
        except Exception as e:  # noqa: BLE001
            print(f"[punisher] kill rpc ended with: {e}")


def kill_loop(
    client: LighthouseClient,
    rng: random.Random,
    mtbf: float,
    menu: tuple = ("exit",),
    deadline: float = float("inf"),
) -> None:
    """Poisson-ish fault schedule with mean time between failures ``mtbf``,
    drawing each fault from ``menu``."""
    while time.monotonic() < deadline:
        delay = rng.expovariate(1.0 / mtbf) if mtbf > 0 else 1.0
        print(f"[punisher] next fault in {delay:.1f}s")
        time.sleep(delay)
        kill_one(client, rng, mode=rng.choice(list(menu)))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--lighthouse",
        default=os.environ.get("TPUFT_LIGHTHOUSE"),
        required=os.environ.get("TPUFT_LIGHTHOUSE") is None,
    )
    parser.add_argument("--seed", type=int, default=None)
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("kill_one")
    sub.add_parser("kill_all")
    fault = sub.add_parser("fault_one")
    fault.add_argument("--mode", choices=FAULT_MODES, default="exit")
    loop = sub.add_parser("kill_loop")
    loop.add_argument("--mtbf", type=float, default=60.0, help="mean seconds between faults")
    loop.add_argument(
        "--menu",
        default="exit",
        help="comma-separated fault modes to draw from: " + ",".join(FAULT_MODES),
    )
    args = parser.parse_args()

    rng = random.Random(args.seed)
    client = LighthouseClient(args.lighthouse)
    if args.cmd == "kill_one":
        kill_one(client, rng)
    elif args.cmd == "kill_all":
        kill_all(client, rng)
    elif args.cmd == "fault_one":
        kill_one(client, rng, mode=args.mode)
    else:
        menu = tuple(m.strip() for m in args.menu.split(",") if m.strip())
        for m in menu:
            if m not in FAULT_MODES:
                parser.error(f"unknown fault mode {m!r}")
        kill_loop(client, rng, args.mtbf, menu=menu)


if __name__ == "__main__":
    main()
