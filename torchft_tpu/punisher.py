"""Chaos tool: inject faults into replica groups of a live job.

Role-equivalent of the reference's ``examples/slurm/punisher.py`` kill CLI
plus the monarch failure menu (examples/monarch/utils/failure.py:25-100):
resolves the current quorum from the lighthouse and fires fault RPCs at
member managers. Process-level modes: exit (process death), segfault
(crash with core), deadlock (coordination wedges while heartbeats
continue), partition (heartbeats + RPC serving stop).

Heal-path modes target the recovery plane itself:

- ``kill_donor_mid_heal``: when the lighthouse shows a joining member, a
  non-joining (donor-capable) member is killed — the joiner must fail
  over and resume the heal from another donor.
- ``corrupt_stream`` / ``stall_donor``: armed through the fault file
  (``$TPUFT_FAULT_FILE`` / ``--fault-file``,
  torchft_tpu/utils/faultinject.py); the next donor chunk-serve consumes
  the arm and flips a payload bit / drips below the joiner's
  minimum-progress floor. Exactly one serve consumes each arm, so
  injected-fault counts stay exact.
- ``kill_serve_child``: armed the same way at the ``serve_child`` site;
  the donor's heal-serving sidecar (``TPUFT_HEAL_SERVE_MODE=child``)
  consumes it at its next chunk serve, finishes that chunk, and dies —
  the joiner must fail over via the resume cache and the donor's step
  loop must observe nothing but a ``report_error``.
- ``kill_donor_mid_stripe``: like ``kill_donor_mid_heal`` but armed only
  when the stripe set survives the kill (a joining member AND at least
  two donor-capable members visible) — the joiner must reassign the dead
  donor's unfetched stripe to the survivors and finish the heal in the
  SAME attempt, re-fetching exactly the dead donor's unverified
  remainder.
- ``corrupt_stripe``: the ``corrupt_stream`` bit-flip, site-tagged to ONE
  donor of a stripe set (``heal_stream:<donor tag>``, usually the serve
  port) so the drill proves a corrupting donor is fenced out of the
  stripe while its peers keep serving.
- ``kill_half_fleet``: the mass-rejoin storm — half the non-joining
  members (floor(n/2), >= 1 survivor kept as donor) are killed at once;
  their supervised relaunches re-enter as SIMULTANEOUS joiners striping
  the same donor set, exercising the coordinated stripe plan, per-joiner
  serve fairness, and the joiner ingress bound.
- ``retract_version``: armed at the ``publisher_retract`` site; the
  targeted publisher's NEXT publish consumes it and immediately
  retracts the just-published version — the rollback-storm drill's
  deterministic trigger ("canary V shipped and was found bad"): every
  resident version >= V is dropped (descriptors, inline chunks, the
  serve child's /dev/shm epochs) and V-1 is re-announced seq-newer, so
  relays and subscribers converge to V-1 with zero torn / stale-era /
  wrong-version adoptions (tests/test_serving.py rollback-storm drill,
  strict AND pipelined orderings; SERVING_BENCH.json rollback leg).
- ``poison_canary``: armed at the ``publisher_canary`` site; the
  targeted publisher's NEXT canary publish consumes it and ships with a
  synthetic bad-quality marker — CRC-valid bytes, integrity chain stays
  green — so only the rollout verdict loop
  (serving/rollout.py RolloutDirector) reacts: shadow evidence turns
  bad, K consecutive windows past the threshold, and the wave is
  auto-retracted fleet-wide (stable tenants never observed it). The
  progressive-delivery drill's deterministic trigger
  (tests/test_rollout.py; SERVING_BENCH.json canary leg).
- ``slow_replica`` / ``wedge_device`` / ``drip_wire``: the GRAY-failure
  arms (torchft_tpu/health.py seams). One arm is consumed by the next
  matching phase — ``slow_replica``/``wedge_device`` at the device-sync
  seam (``device_sync``), ``drip_wire`` at the wire-bucket seam
  (``wire``) — and installs a PERSISTENT per-replica fault in the
  consuming process: a per-step stall, a device sync that never
  completes (heartbeats continue — the fully-wedged mode), or a
  dripping per-bucket wire stall. The health plane must verdict and
  self-eject the victim (``TPUFT_HEALTH=1``); ejection/restart clears
  the fault, so the victim's comeback is clean.
- ``kill_relay``: armed at the ``serving_relay`` site (optionally
  ``--donor-tag <port>`` to target one relay of a tier — in a relay
  TREE that is how an INTERIOR relay is singled out, since every tier
  speaks the same protocol and shares the site family); the next relay
  poll round, reader GET, or parked ``/serving/notify`` long-poll
  consumes it and the relay dies abruptly mid-service — downstream
  relays and subscribers must re-home to a sibling/parent announcing
  the same digest without ever observing a torn or stale-era version
  (the serving plane's chaos drills, tests/test_serving.py +
  tests/test_serving_tree.py; benchmarks/relay_tree_bench.py SIGKILLs
  whole interior relay processes for the out-of-process variant).

    python -m torchft_tpu.punisher --lighthouse host:29510 kill_one
    python -m torchft_tpu.punisher --lighthouse host:29510 fault_one --mode deadlock
    python -m torchft_tpu.punisher --lighthouse host:29510 --fault-file /tmp/f \
        fault_one --mode corrupt_stream
    python -m torchft_tpu.punisher --lighthouse host:29510 kill_loop --mtbf 60 \
        --menu exit,segfault,deadlock,partition,kill_donor_mid_heal
"""

from __future__ import annotations

import argparse
import os
import random
import time
from typing import Optional

from torchft_tpu.coordination import LighthouseClient
from torchft_tpu.utils import faultinject

__all__ = [
    "kill_one",
    "kill_all",
    "kill_loop",
    "kill_donor_mid_heal",
    "kill_donor_mid_stripe",
    "kill_half_fleet",
    "arm_stream_fault",
    "inject_fault",
    "main",
    "FAULT_MODES",
    "HEAL_FAULT_MODES",
    "SERVING_FAULT_MODES",
    "HEALTH_FAULT_MODES",
    "ALL_FAULT_MODES",
]


def _members(client: LighthouseClient):
    status = client.status()
    return [m.member.replica_id for m in status.members if not m.joining]


# Modes the native manager's kill RPC executes in-process.
FAULT_MODES = ("exit", "segfault", "deadlock", "partition")
# Heal-plane modes delivered outside the kill RPC (status-targeted kill /
# file-armed stream faults / the serve-sidecar kill / the stripe-targeted
# variants).
HEAL_FAULT_MODES = (
    "kill_donor_mid_heal",
    "corrupt_stream",
    "stall_donor",
    "kill_serve_child",
    "kill_donor_mid_stripe",
    "corrupt_stripe",
    "corrupt_quantized_chunk",
    "kill_half_fleet",
)
# Serving-plane modes (the committed-weights fan-out tier).
SERVING_FAULT_MODES = ("kill_relay", "retract_version", "poison_canary")
# Gray-failure modes (the health plane's slow-is-the-new-dead drills):
# file-armed persistent stalls/wedges at the device-sync and wire seams.
HEALTH_FAULT_MODES = ("slow_replica", "wedge_device", "drip_wire")
ALL_FAULT_MODES = (
    FAULT_MODES + HEAL_FAULT_MODES + SERVING_FAULT_MODES + HEALTH_FAULT_MODES
)


def kill_one(
    client: LighthouseClient, rng: random.Random, mode: str = "exit"
) -> bool:
    members = _members(client)
    if not members:
        print("[punisher] no quorum members to kill")
        return False
    victim = rng.choice(members)
    print(f"[punisher] injecting {mode} into {victim}")
    try:
        client.kill(victim, mode=mode)
    except Exception as e:  # noqa: BLE001  — victim may die before replying
        print(f"[punisher] kill rpc ended with: {e}")
    return True


def kill_donor_mid_heal(client: LighthouseClient, rng: random.Random) -> bool:
    """Kills a donor-capable member while a heal is in flight (a joining
    member is visible in the lighthouse status). No heal in flight = no-op:
    this fault only makes sense against recovery traffic."""
    try:
        status = client.status()
    except Exception as e:  # noqa: BLE001
        print(f"[punisher] status rpc ended with: {e}")
        return False
    joining = [m.member.replica_id for m in status.members if m.joining]
    donors = [m.member.replica_id for m in status.members if not m.joining]
    if not joining or not donors:
        print("[punisher] no heal in flight; skipping kill_donor_mid_heal")
        return False
    victim = rng.choice(donors)
    print(
        f"[punisher] killing donor-side member {victim} while "
        f"{joining} heal(s)"
    )
    try:
        client.kill(victim, mode="exit")
    except Exception as e:  # noqa: BLE001
        print(f"[punisher] kill rpc ended with: {e}")
    return True


def kill_half_fleet(client: LighthouseClient, rng: random.Random) -> bool:
    """The mass-rejoin storm fault: kills HALF the non-joining members at
    once (floor(n/2), always leaving at least one survivor to donor the
    storm), status-targeted like kill_donor_mid_heal. The supervised
    victims all relaunch together and re-enter the next quorums as
    simultaneous joiners striping the same donor set — the scenario the
    coordinated stripe plan, per-joiner serve fairness, and joiner
    ingress bound exist for. Needs >= 2 killable members (one kill is
    just kill_one)."""
    try:
        status = client.status()
    except Exception as e:  # noqa: BLE001
        print(f"[punisher] status rpc ended with: {e}")
        return False
    donors = [m.member.replica_id for m in status.members if not m.joining]
    if len(donors) < 2:
        print(
            f"[punisher] only {len(donors)} killable member(s); "
            "skipping kill_half_fleet"
        )
        return False
    victims = rng.sample(donors, len(donors) // 2)
    print(
        f"[punisher] storm: killing {len(victims)} of {len(donors)} "
        f"members at once: {victims}"
    )
    for victim in victims:
        try:
            client.kill(victim, mode="exit")
        except Exception as e:  # noqa: BLE001
            print(f"[punisher] kill rpc ended with: {e}")
    return True


def kill_donor_mid_stripe(client: LighthouseClient, rng: random.Random) -> bool:
    """Kills one of N active donors while a STRIPED heal is in flight: a
    joining member must be visible AND at least two donor-capable members
    must remain serving, so the joiner's stripe reassignment (not the
    cross-attempt failover) is the mechanism under test. Fewer donors =
    no-op (kill_donor_mid_heal covers the single-donor failover path)."""
    try:
        status = client.status()
    except Exception as e:  # noqa: BLE001
        print(f"[punisher] status rpc ended with: {e}")
        return False
    joining = [m.member.replica_id for m in status.members if m.joining]
    donors = [m.member.replica_id for m in status.members if not m.joining]
    if not joining or len(donors) < 2:
        print(
            "[punisher] no striped heal in flight "
            f"({len(joining)} joining, {len(donors)} donors); "
            "skipping kill_donor_mid_stripe"
        )
        return False
    victim = rng.choice(donors)
    print(
        f"[punisher] killing stripe donor {victim} "
        f"({len(donors) - 1} donors survive for {joining})"
    )
    try:
        client.kill(victim, mode="exit")
    except Exception as e:  # noqa: BLE001
        print(f"[punisher] kill rpc ended with: {e}")
    return True


def arm_stream_fault(
    mode: str,
    fault_file: Optional[str] = None,
    donor_tag: Optional[str] = None,
) -> bool:
    """Arms a donor-serve fault via the fault file: stream faults
    (``corrupt_stream``/``stall_donor``) are consumed by the next donor
    chunk-serve in EITHER serve mode; ``kill_serve_child`` is consumed
    only by a serving sidecar (site ``serve_child``) and kills it;
    ``corrupt_stripe`` is the same bit-flip as ``corrupt_stream`` but
    site-tagged to one donor of a stripe set (``--donor-tag``, usually
    the victim's serve port — untagged it behaves like corrupt_stream,
    hitting whichever stripe serves next); ``kill_relay`` arms a ``die``
    at the ``serving_relay`` site (``--donor-tag`` = the relay's serve
    port to target one relay of a tier) — the victim relay drops
    abruptly at its next poll round or reader GET."""
    if mode == "kill_serve_child":
        site, armed_mode = "serve_child", mode
    elif mode in ("corrupt_stripe", "corrupt_quantized_chunk"):
        # corrupt_quantized_chunk: the same bit-flip, aimed at a donor
        # staged with TPUFT_HEAL_CODEC — the drill that proves the CRC
        # (computed over ENCODED bytes) catches corruption in the
        # compressed payload exactly like in raw f32, and a decode of
        # tampered-but-CRC-clean bytes can still never be adopted
        # (tests/test_wire_codec.py).
        site = f"heal_stream:{donor_tag}" if donor_tag else "heal_stream"
        armed_mode = "corrupt_stream"  # the serve seam knows one bit-flip
    elif mode == "kill_relay":
        # The relay consumes "die" at its poll loop and serve handler;
        # the tag (its serve port) narrows the kill to one relay of a
        # fan-out tier.
        site = f"serving_relay:{donor_tag}" if donor_tag else "serving_relay"
        armed_mode = "die"
    elif mode == "retract_version":
        # The publisher consumes "retract" right after its next publish
        # and retracts that version fleet-wide (readers converge to V-1).
        site = "publisher_retract"
        armed_mode = "retract"
    elif mode == "poison_canary":
        # The publisher consumes "poison" at its next canary publish and
        # ships it with a synthetic bad-quality marker (CRC-valid); the
        # rollout verdict loop — not the integrity chain — must retract.
        site = "publisher_canary"
        armed_mode = "poison"
    elif mode in ("slow_replica", "wedge_device"):
        # Consumed by the next device sync anywhere in the fleet; the
        # consumer installs a persistent per-replica gray fault
        # (health.injected_stall) — the health plane's verdict/ejection
        # machinery is what recovers the fleet, not this arm.
        site = "device_sync"
        armed_mode = mode
    elif mode == "drip_wire":
        site = "wire"
        armed_mode = mode
    else:
        site, armed_mode = "heal_stream", mode
    try:
        path = faultinject.arm(armed_mode, path=fault_file, site=site)
    except ValueError as e:
        print(f"[punisher] cannot arm {mode}: {e}")
        return False
    print(f"[punisher] armed {mode} at {path} (site {site})")
    return True


def inject_fault(
    client: LighthouseClient,
    rng: random.Random,
    mode: str,
    fault_file: Optional[str] = None,
) -> bool:
    """Dispatches one fault from the full menu; returns whether a fault was
    actually delivered (heal-plane modes no-op without their trigger)."""
    if mode in FAULT_MODES:
        return kill_one(client, rng, mode=mode)
    if mode == "kill_donor_mid_heal":
        return kill_donor_mid_heal(client, rng)
    if mode == "kill_donor_mid_stripe":
        return kill_donor_mid_stripe(client, rng)
    if mode == "kill_half_fleet":
        return kill_half_fleet(client, rng)
    if mode in (
        "corrupt_stream",
        "stall_donor",
        "kill_serve_child",
        "corrupt_stripe",
        "corrupt_quantized_chunk",
        "kill_relay",
        "retract_version",
        "poison_canary",
    ) or mode in HEALTH_FAULT_MODES:
        return arm_stream_fault(mode, fault_file)
    raise ValueError(f"unknown fault mode {mode!r}")


def kill_all(client: LighthouseClient, rng: random.Random) -> None:
    for victim in _members(client):
        print(f"[punisher] killing {victim}")
        try:
            client.kill(victim)
        except Exception as e:  # noqa: BLE001
            print(f"[punisher] kill rpc ended with: {e}")


def kill_loop(
    client: LighthouseClient,
    rng: random.Random,
    mtbf: float,
    menu: tuple = ("exit",),
    deadline: float = float("inf"),
    fault_file: Optional[str] = None,
) -> None:
    """Poisson-ish fault schedule with mean time between failures ``mtbf``,
    drawing each fault from ``menu``."""
    while time.monotonic() < deadline:
        delay = rng.expovariate(1.0 / mtbf) if mtbf > 0 else 1.0
        print(f"[punisher] next fault in {delay:.1f}s")
        time.sleep(delay)
        inject_fault(client, rng, rng.choice(list(menu)), fault_file=fault_file)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--lighthouse",
        default=os.environ.get("TPUFT_LIGHTHOUSE"),
        required=os.environ.get("TPUFT_LIGHTHOUSE") is None,
    )
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument(
        "--fault-file",
        default=os.environ.get(faultinject.ENV_FAULT_FILE),
        help="file the stream faults are armed through (the job must run "
        f"with ${faultinject.ENV_FAULT_FILE} pointing at the same path)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("kill_one")
    sub.add_parser("kill_all")
    fault = sub.add_parser("fault_one")
    fault.add_argument("--mode", choices=ALL_FAULT_MODES, default="exit")
    fault.add_argument(
        "--donor-tag",
        default=None,
        help="corrupt_stripe / kill_relay: target one donor of a stripe "
        "set (or one relay of a tier) by its serve-site tag (usually the "
        "serve port)",
    )
    loop = sub.add_parser("kill_loop")
    loop.add_argument("--mtbf", type=float, default=60.0, help="mean seconds between faults")
    loop.add_argument(
        "--menu",
        default="exit",
        help="comma-separated fault modes to draw from: " + ",".join(ALL_FAULT_MODES),
    )
    args = parser.parse_args()

    rng = random.Random(args.seed)
    client = LighthouseClient(args.lighthouse)
    if args.cmd == "kill_one":
        kill_one(client, rng)
    elif args.cmd == "kill_all":
        kill_all(client, rng)
    elif args.cmd == "fault_one":
        if args.mode in ("corrupt_stripe", "kill_relay") and args.donor_tag:
            arm_stream_fault(
                args.mode, args.fault_file, donor_tag=args.donor_tag
            )
        else:
            inject_fault(client, rng, args.mode, fault_file=args.fault_file)
    else:
        menu = tuple(m.strip() for m in args.menu.split(",") if m.strip())
        for m in menu:
            if m not in ALL_FAULT_MODES:
                parser.error(f"unknown fault mode {m!r}")
        kill_loop(client, rng, args.mtbf, menu=menu, fault_file=args.fault_file)


if __name__ == "__main__":
    main()
