"""Chaos tool: kill replica groups of a live job.

Role-equivalent of the reference's ``examples/slurm/punisher.py`` kill_one/
kill_all/kill_loop CLI: resolves the current quorum from the lighthouse and
fires Kill RPCs at member managers (which ``exit(1)``, exactly as the
dashboard's kill button does).

    python -m torchft_tpu.punisher --lighthouse host:29510 kill_one
    python -m torchft_tpu.punisher --lighthouse host:29510 kill_loop --mtbf 60
"""

from __future__ import annotations

import argparse
import os
import random
import time

from torchft_tpu.coordination import LighthouseClient

__all__ = ["kill_one", "kill_all", "kill_loop", "main"]


def _members(client: LighthouseClient):
    status = client.status()
    return [m.member.replica_id for m in status.members if not m.joining]


def kill_one(client: LighthouseClient, rng: random.Random) -> None:
    members = _members(client)
    if not members:
        print("[punisher] no quorum members to kill")
        return
    victim = rng.choice(members)
    print(f"[punisher] killing {victim}")
    try:
        client.kill(victim)
    except Exception as e:  # noqa: BLE001  — victim may die before replying
        print(f"[punisher] kill rpc ended with: {e}")


def kill_all(client: LighthouseClient, rng: random.Random) -> None:
    for victim in _members(client):
        print(f"[punisher] killing {victim}")
        try:
            client.kill(victim)
        except Exception as e:  # noqa: BLE001
            print(f"[punisher] kill rpc ended with: {e}")


def kill_loop(client: LighthouseClient, rng: random.Random, mtbf: float) -> None:
    """Poisson-ish kill schedule with mean time between failures ``mtbf``."""
    while True:
        delay = rng.expovariate(1.0 / mtbf) if mtbf > 0 else 1.0
        print(f"[punisher] next kill in {delay:.1f}s")
        time.sleep(delay)
        kill_one(client, rng)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--lighthouse",
        default=os.environ.get("TPUFT_LIGHTHOUSE"),
        required=os.environ.get("TPUFT_LIGHTHOUSE") is None,
    )
    parser.add_argument("--seed", type=int, default=None)
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("kill_one")
    sub.add_parser("kill_all")
    loop = sub.add_parser("kill_loop")
    loop.add_argument("--mtbf", type=float, default=60.0, help="mean seconds between kills")
    args = parser.parse_args()

    rng = random.Random(args.seed)
    client = LighthouseClient(args.lighthouse)
    if args.cmd == "kill_one":
        kill_one(client, rng)
    elif args.cmd == "kill_all":
        kill_all(client, rng)
    else:
        kill_loop(client, rng, args.mtbf)


if __name__ == "__main__":
    main()
