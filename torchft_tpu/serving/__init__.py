"""Committed-weights serving plane.

The training fleet's answer to "serve heavy traffic from millions of
users": every committed step's params become an immutable, quorum-era-
tagged, integrity-digested snapshot in the heal plane's exact chunk
format, published without stalling the step loop and fanned out through
a caching relay tier to arbitrarily many readers.

Roles:

- :class:`WeightPublisher` (publisher.py) — publication, driven by the
  manager's commit hooks; speculative-window state is structurally never
  published (analyzer rule R7 pins the drain-before-publish ordering).
- :class:`CachingRelay` (relay.py) — delta-aware pulls, in-memory chunk
  cache, upstream failover mid-pull, stackable.
- :class:`WeightSubscriber` (subscriber.py) — verify-then-swap reader;
  torn, stale-era, or rolled-back versions are structurally unobservable.
- ``rollout`` (rollout.py) — progressive-delivery policy plane: tenant →
  stream resolution, shadow reads, and the quality-gated verdict loop.

Exports resolve lazily (PEP 562): ``rollout`` is jax-free and importable
from the serve child and from ``checkpointing.http_transport`` without
dragging in the publisher→transport or subscriber→jax import chains.

docs/serving.md has the architecture, version lifecycle, and failure
rows; benchmarks/serving_bench.py measures reader throughput under
fleet chaos.
"""

import importlib

# name -> submodule holding it; resolved on first attribute access so that
# `from torchft_tpu.serving import rollout` (used by the jax-free serve
# child and by http_transport, which publisher itself imports) never
# executes the heavier publisher/relay/subscriber module bodies.
_EXPORTS = {
    "ENV_NOTIFY": "_wire",
    "ENV_NOTIFY_HOLD_SEC": "_wire",
    "PollPacer": "_wire",
    "notify_enabled": "_wire",
    "notify_hold_sec": "_wire",
    "ENV_PUBLISH_CHUNKS": "publisher",
    "ENV_PUBLISH_EVERY": "publisher",
    "WeightPublisher": "publisher",
    "publish_every": "publisher",
    "ENV_SERVING_POLL_SEC": "relay",
    "CachingRelay": "relay",
    "serving_poll_sec": "relay",
    "ServingVersion": "subscriber",
    "WeightSubscriber": "subscriber",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    module = importlib.import_module(f"{__name__}.{submodule}")
    value = getattr(module, name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
