"""Committed-weights serving plane.

The training fleet's answer to "serve heavy traffic from millions of
users": every committed step's params become an immutable, quorum-era-
tagged, integrity-digested snapshot in the heal plane's exact chunk
format, published without stalling the step loop and fanned out through
a caching relay tier to arbitrarily many readers.

Roles:

- :class:`WeightPublisher` (publisher.py) — publication, driven by the
  manager's commit hooks; speculative-window state is structurally never
  published (analyzer rule R7 pins the drain-before-publish ordering).
- :class:`CachingRelay` (relay.py) — delta-aware pulls, in-memory chunk
  cache, upstream failover mid-pull, stackable.
- :class:`WeightSubscriber` (subscriber.py) — verify-then-swap reader;
  torn, stale-era, or rolled-back versions are structurally unobservable.

docs/serving.md has the architecture, version lifecycle, and failure
rows; benchmarks/serving_bench.py measures reader throughput under
fleet chaos.
"""

from torchft_tpu.serving._wire import (
    ENV_NOTIFY,
    ENV_NOTIFY_HOLD_SEC,
    PollPacer,
    notify_enabled,
    notify_hold_sec,
)
from torchft_tpu.serving.publisher import (
    ENV_PUBLISH_CHUNKS,
    ENV_PUBLISH_EVERY,
    WeightPublisher,
    publish_every,
)
from torchft_tpu.serving.relay import (
    ENV_SERVING_POLL_SEC,
    CachingRelay,
    serving_poll_sec,
)
from torchft_tpu.serving.subscriber import ServingVersion, WeightSubscriber

__all__ = [
    "WeightPublisher",
    "CachingRelay",
    "WeightSubscriber",
    "ServingVersion",
    "PollPacer",
    "ENV_PUBLISH_EVERY",
    "ENV_PUBLISH_CHUNKS",
    "ENV_SERVING_POLL_SEC",
    "ENV_NOTIFY",
    "ENV_NOTIFY_HOLD_SEC",
    "publish_every",
    "serving_poll_sec",
    "notify_enabled",
    "notify_hold_sec",
]
