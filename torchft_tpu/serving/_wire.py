"""Shared wire helpers for the committed-weights serving plane.

The serving plane speaks the heal plane's exact chunk protocol
(checkpointing/http_transport.py: pickled ``/checkpoint/{step}/meta``,
raw ``/checkpoint/{step}/{i}`` chunk bodies, per-chunk CRCs bound into a
whole-checkpoint sha256 digest) plus two JSON announcement routes:

- ``/serving/latest`` — the version descriptor a publisher or relay
  serves so readers can discover the newest fully staged version without
  unpickling anything;
- ``/serving/notify?after=<step>&hold=<sec>`` — the long-poll twin: the
  request PARKS (bounded hold) until the server announces a version
  newer than ``after``, then answers with the same descriptor body (204
  on hold expiry — the client re-arms). A publish therefore propagates
  down a relay tree in ~one wire RTT per hop instead of one poll
  interval per hop; verification is unchanged (the descriptor a notify
  delivers goes through the identical digest-binding / era checks, so
  push is purely a latency plane, never a trust plane).

These helpers keep the three roles (publisher / relay / subscriber)
byte-compatible, and pin the emulated-DCN shim (utils/netem.py) at the
client fetch seam: every serving-plane pull charges the emulated link's
RTT + serialization, EXCEPT the response leg of bodies a netem-paced
server already charged (it declares ``netem.PACED_HEADER``), so no hop
is double-billed regardless of which side carries the shim.

Serving requests may carry a tenant bearer token
(``Authorization: Bearer <token>``; TPUFT_SERVING_TENANT_TOKENS) —
the multi-tenant egress fairness identity, checked at every serve seam.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from torchft_tpu import metrics
from torchft_tpu.checkpointing.http_transport import (
    _CRC_UPDATERS,
    _checkpoint_digest,
)
from torchft_tpu.utils import netem

__all__ = [
    "LATEST_ROUTE",
    "NOTIFY_ROUTE",
    "VERSION_ROUTE_PREFIX",
    "LATEST_PREV_ROUTE",
    "ENV_NOTIFY",
    "ENV_NOTIFY_HOLD_SEC",
    "notify_enabled",
    "notify_hold_sec",
    "fetch_json",
    "fetch_bytes",
    "fetch_notify",
    "latest_descriptor",
    "validate_latest",
    "newer_than_held",
    "same_stream",
    "changed_chunks_between",
    "chunk_crc",
    "NotifyHub",
    "serve_notify",
    "PollPacer",
]

LATEST_ROUTE = "/serving/latest"
NOTIFY_ROUTE = "/serving/notify"
# Pinned-version discovery (the history ring's read surface):
# ``/serving/version/{step}`` answers that exact resident version's
# descriptor (410 once retracted), ``/serving/latest-1`` the previous
# resident version — canary/A-B reads and the rollback fallback.
VERSION_ROUTE_PREFIX = "/serving/version/"
LATEST_PREV_ROUTE = "/serving/latest-1"

ENV_NOTIFY = "TPUFT_SERVING_NOTIFY"
ENV_NOTIFY_HOLD_SEC = "TPUFT_SERVING_NOTIFY_HOLD_SEC"


def notify_enabled(default: bool = True) -> bool:
    """Long-poll push switch (``$TPUFT_SERVING_NOTIFY``; default on).
    Off, or against an upstream that does not speak the route, the plane
    degrades to the jittered poll loop — push is a latency optimization,
    never a correctness dependency."""
    raw = os.environ.get(ENV_NOTIFY)
    if raw is None:
        return default
    return raw not in ("", "0")


def notify_hold_sec(default: float = 25.0) -> float:
    """Maximum seconds one notify request may park server-side
    (``$TPUFT_SERVING_NOTIFY_HOLD_SEC``). Bounded so a dead client's
    handler thread is reclaimed and an idle tier re-arms on a heartbeat
    cadence; clients re-issue on 204, so the hold length only trades
    re-arm traffic against thread residency, never propagation latency."""
    try:
        return max(0.05, float(os.environ.get(ENV_NOTIFY_HOLD_SEC, str(default))))
    except ValueError:
        return default


class CancelScope:
    """Cross-thread abort for a parked long-poll GET. A notify request
    blocks in ``resp.read()`` for up to the server-side hold; a relay
    shutting down cannot wait that out, so its shutdown closes the scope
    and the in-flight socket is torn down from under the read (which
    raises immediately into the caller's failover path). One-shot:
    attaching to a closed scope aborts the response on the spot."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._resp: Any = None
        self._closed = False

    def _abort(self, obj: Any) -> None:
        # socket.shutdown is the only call that reliably unblocks a recv()
        # parked in another thread; close() alone may not.
        sock = getattr(obj, "sock", None)  # http.client.HTTPConnection
        if sock is None:
            fp = getattr(obj, "fp", None)  # http.client.HTTPResponse
            sock = getattr(getattr(fp, "raw", None), "_sock", None)
        try:
            if sock is not None:
                sock.shutdown(socket.SHUT_RDWR)
        except Exception:  # noqa: BLE001 — already closed / exotic transport
            pass
        try:
            obj.close()
        except Exception:  # noqa: BLE001
            pass

    def attach(self, obj: Any) -> None:
        with self._lock:
            self._resp = obj
            if self._closed:
                self._abort(obj)

    def detach(self) -> None:
        with self._lock:
            self._resp = None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._resp is not None:
                self._abort(self._resp)
                self._resp = None


def _fetch(
    url: str,
    timeout: float,
    token: Optional[str],
    cancel: Optional[CancelScope] = None,
) -> Any:
    """One GET with the netem link charged at this CLIENT seam: request
    leg up front, response leg (latency + serialization) after the read —
    unless the server declared it already paced the body.

    With ``cancel``, the connection itself goes through http.client so the
    scope owns it BEFORE any byte arrives — a long-poll server parks the
    whole response (status line included), so aborting only a response
    object obtained from urlopen would be too late."""
    link = netem.enabled()
    if link:
        netem.pace_latency()  # request leg
    if cancel is not None:
        parsed = urllib.parse.urlsplit(url)
        conn = http.client.HTTPConnection(
            parsed.hostname, parsed.port, timeout=timeout
        )
        cancel.attach(conn)
        try:
            path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
            headers = {"Authorization": f"Bearer {token}"} if token else {}
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
            body = resp.read()
            server_paced = resp.headers.get(netem.PACED_HEADER) == "1"
            status = resp.status
        finally:
            cancel.detach()
            conn.close()
        if status >= 400:
            raise urllib.error.HTTPError(url, status, "fetch failed", None, None)
    else:
        request = urllib.request.Request(url)
        if token:
            request.add_header("Authorization", f"Bearer {token}")
        resp = urllib.request.urlopen(request, timeout=timeout)
        try:
            body = resp.read()
            server_paced = resp.headers.get(netem.PACED_HEADER) == "1"
            status = resp.status
        finally:
            resp.close()
    if link and not server_paced:
        netem.pace(len(body))  # response leg: RTT/2 + bytes/bandwidth
    return body, status


def fetch_json(url: str, timeout: float, token: Optional[str] = None) -> Dict[str, Any]:
    """One JSON GET (no retry — serving readers fail over across
    endpoints instead of betting a retry window on one)."""
    body, _ = _fetch(url, timeout, token)
    data = json.loads(body)
    if not isinstance(data, dict):
        raise ValueError(f"expected a JSON object from {url}")
    return data


def fetch_bytes(url: str, timeout: float, token: Optional[str] = None) -> bytes:
    body, _ = _fetch(url, timeout, token)
    return body


def fetch_notify(
    base: str,
    after: int,
    timeout: float,
    token: Optional[str] = None,
    hold: Optional[float] = None,
    after_seq: Optional[int] = None,
    after_pub: Optional[str] = None,
    cancel: Optional[CancelScope] = None,
    stream: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """One long-poll round against ``base``: parks server-side until a
    version newer than ``after`` is announced (bounded by ``hold``) and
    returns its descriptor, or None when the hold expired with nothing
    new (the caller re-arms). ``after_seq`` is the held version's
    publication sequence — against a seq-aware server it makes a
    RETRACTION (lower step, higher pub_seq) wake the waiter too, which
    step watermarks alone cannot express. ``stream`` requests a rollout
    view (``stable``/``canary``/``all`` — serving/rollout.py); the
    server resolves it against the token's tenant policy exactly like a
    polled discovery route. The descriptor is NOT trusted — callers run
    it through the same validation a polled ``/serving/latest`` body
    gets."""
    hold = hold if hold is not None else notify_hold_sec()
    url = f"{base}{NOTIFY_ROUTE}?after={int(after)}&hold={hold:g}"
    if after_seq is not None:
        url += f"&after_seq={int(after_seq)}"
    if after_pub:
        url += f"&after_pub={urllib.parse.quote(str(after_pub))}"
    if stream:
        url += f"&stream={urllib.parse.quote(str(stream))}"
    # The socket timeout must outlive the server-side hold.
    body, status = _fetch(url, hold + timeout, token, cancel=cancel)
    if status == 204 or not body:
        return None
    data = json.loads(body)
    if not isinstance(data, dict):
        raise ValueError(f"expected a JSON descriptor from {url}")
    return data


def latest_descriptor(
    manifest: Dict[str, Any],
    base: str,
    published_ts: float,
    depth: int = 0,
    origin_ts: Optional[float] = None,
    pub_seq: Optional[int] = None,
    pub_id: Optional[str] = None,
    region: Optional[str] = None,
    stream: Optional[str] = None,
    poisoned: bool = False,
) -> Dict[str, Any]:
    """The ``/serving/latest`` body: the staging manifest
    (http_transport._stage_manifest) plus where to fetch the chunks from
    (``base`` — the publisher's transport/sidecar or a relay), when THIS
    tier went live (``published_ts``), the serving node's tree depth
    (publisher = 0, each relay tier +1 — fleet_status's RELAY column),
    and the ORIGIN publication time (``origin_ts``, preserved across
    tiers so publish-to-edge propagation is measurable end to end).
    ``region`` advertises which WAN region this tier serves FROM (an edge
    relay's readers use it to pick the nearest tier) — advisory routing
    metadata only, never part of the verify-then-swap integrity chain."""
    descriptor = dict(manifest)
    descriptor["format"] = 1
    descriptor["base"] = base
    descriptor["published_ts"] = published_ts
    descriptor["depth"] = depth
    descriptor["origin_ts"] = origin_ts if origin_ts is not None else published_ts
    if region is not None:
        descriptor["region"] = region
    if pub_seq is not None:
        # Publication sequence: monotone over publishes AND retractions,
        # preserved across relay tiers. It is what lets a deliberate
        # rollback (step DECREASES, seq increases) outrank the retracted
        # version while a stale endpoint (old seq) still cannot roll a
        # reader back. Scoped by "pub_id" (the originating publisher's
        # stream identity): sequences from DIFFERENT publishers are
        # incomparable counters, so cross-publisher failover falls back
        # to era/step ordering.
        descriptor["pub_seq"] = int(pub_seq)
    if pub_id is not None:
        descriptor["pub_id"] = str(pub_id)
    if stream is not None:
        # Progressive delivery (serving/rollout.py): which rollout
        # stream this version belongs to ("canary" until promoted).
        # Publication-plane metadata like pub_seq — it rides the
        # announce chain and relay tiers verbatim, and is never part of
        # the digest/CRC integrity binding; stream ENFORCEMENT happens
        # at the serve seams and reader-side, both before verification.
        descriptor["stream"] = str(stream)
    if poisoned:
        # Punisher poison_canary marker: synthetic "this canary is bad"
        # quality evidence — CRC-valid bytes, so only the rollout
        # verdict loop (never the integrity chain) reacts to it.
        descriptor["poisoned"] = True
    return descriptor


def same_stream(
    latest: Dict[str, Any], held_seq: Optional[int], held_src: Optional[str]
) -> bool:
    """True when ``latest`` continues the publication stream the held
    version came from — both carry a sequence and the originating
    publisher identity matches — i.e. pub_seq ordering is meaningful."""
    return (
        latest.get("pub_seq") is not None
        and held_seq is not None
        and latest.get("pub_id") == held_src
    )


def newer_than_held(
    latest: Dict[str, Any],
    held_step: int,
    held_seq: Optional[int] = None,
    held_src: Optional[str] = None,
) -> bool:
    """Version ordering against a held version: publication sequence
    within one publisher stream (a retraction is seq-newer at a LOWER
    step), step order otherwise (cross-publisher failover and the
    pre-history wire contract). Era fencing stays the caller's separate
    check — suspended only under same-stream seq ordering, where an era
    regression is a sanctioned rollback, not a stale survivor."""
    if same_stream(latest, held_seq, held_src):
        return int(latest["pub_seq"]) > int(held_seq)  # type: ignore[arg-type]
    return int(latest["step"]) > held_step


def changed_chunks_between(
    base: Optional[Dict[str, Any]], latest: Dict[str, Any]
) -> Optional[List[int]]:
    """Chunk indices whose ``(crc, size)`` differ between two manifests
    of the SAME chunk layout; None when the layouts are incomparable.
    Serves the delta-aware notify body — advisory only: readers verify
    every adopted chunk against the descriptor CRCs regardless, so a
    lying set can waste a fetch, never corrupt an adoption."""
    if base is None:
        return None
    try:
        base_crcs, base_sizes = base["chunk_crcs"], base["chunk_sizes"]
        crcs, sizes = latest["chunk_crcs"], latest["chunk_sizes"]
    except KeyError:
        return None
    if (
        base.get("crc_algo") != latest.get("crc_algo")
        or len(base_crcs) != len(crcs)
        or len(base_sizes) != len(sizes)
    ):
        return None
    return [
        i
        for i in range(len(crcs))
        if base_crcs[i] != crcs[i] or base_sizes[i] != sizes[i]
    ]


def validate_latest(latest: Dict[str, Any]) -> Optional[str]:
    """Structural + integrity-binding validation of a ``/serving/latest``
    descriptor; returns a rejection reason or None when acceptable. The
    digest MUST be exactly the binding of (step, algo, chunk_crcs) — and
    of the per-chunk codec tags when the version is codec-encoded —
    checked before any chunk transfer, so a torn or tampered descriptor
    (including a tampered codec tag) never costs a payload fetch and can
    never be adopted."""
    if latest.get("format") != 1:
        return f"unrecognized /serving/latest format: {latest.get('format')!r}"
    for key in ("step", "digest", "crc_algo", "chunk_crcs", "chunk_sizes", "base"):
        if latest.get(key) is None:
            return f"/serving/latest missing {key!r}"
    algo = latest["crc_algo"]
    if algo not in _CRC_UPDATERS:
        return f"descriptor checksums use {algo!r}, unavailable on this host"
    crcs: List[int] = latest["chunk_crcs"]
    sizes: List[int] = latest["chunk_sizes"]
    if len(crcs) != len(sizes) or len(crcs) != int(latest.get("num_chunks", len(crcs))):
        return "descriptor chunk_crcs/chunk_sizes/num_chunks disagree"
    codecs = latest.get("chunk_codecs")
    if codecs is not None:
        from torchft_tpu import wire_codec

        if (
            not isinstance(codecs, list)
            or len(codecs) != len(crcs)
            or any(c not in wire_codec.CODECS for c in codecs)
        ):
            return f"descriptor carries an invalid chunk_codecs list: {codecs!r}"
    if (
        _checkpoint_digest(int(latest["step"]), algo, crcs, codecs)
        != latest["digest"]
    ):
        return "descriptor digest does not bind its per-chunk checksums/codecs"
    return None


def chunk_crc(data: bytes, algo: str) -> int:
    update: Callable[[int, Any], int] = _CRC_UPDATERS[algo]
    return update(0, data)


class NotifyHub:
    """Server-side long-poll rendezvous: handler threads park in
    :meth:`wait_newer` until :meth:`announce` moves the newest step past
    their ``after`` watermark (or the bounded hold expires). One hub per
    serving node (publisher announce server / relay); ``close()`` wakes
    every waiter so shutdown and the punisher's ``kill_relay`` never
    strand a parked reader past its hold."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._latest = -1
        self._latest_seq = -1
        self._closed = False
        self._waiters = 0

    def announce(self, step: int, seq: Optional[int] = None) -> None:
        """A new version went live. ``seq`` (the publication sequence)
        moves independently of ``step`` so a RETRACTION — lower step,
        higher seq — still wakes seq-aware waiters; step-only waiters
        (the pre-history wire) keep their step watermark semantics."""
        with self._cond:
            woke = False
            if step > self._latest:
                self._latest = step
                woke = True
            if seq is not None and seq > self._latest_seq:
                self._latest_seq = seq
                woke = True
            if woke:
                self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def wait_newer(
        self, after: int, hold: float, after_seq: Optional[int] = None
    ) -> bool:
        """Parks until a version newer than the watermark was announced;
        True when one is available (False = hold expired / hub closed).
        The watermark is ``after_seq`` (publication sequence) when the
        client sent one, the step otherwise."""

        def newer() -> bool:
            if after_seq is not None and self._latest_seq >= 0:
                return self._latest_seq > after_seq
            return self._latest > after

        with self._cond:
            self._waiters += 1
            metrics.set_gauge("tpuft_serving_notify_waiters", self._waiters)
            try:
                self._cond.wait_for(
                    lambda: self._closed or newer(), timeout=hold
                )
                return newer()
            finally:
                self._waiters -= 1
                metrics.set_gauge("tpuft_serving_notify_waiters", self._waiters)


def serve_notify(
    handler: Any,
    query: str,
    hub: NotifyHub,
    descriptor: Callable[[], Optional[Dict[str, Any]]],
    manifest_at: Optional[Callable[[int], Optional[Dict[str, Any]]]] = None,
) -> None:
    """The ``/serving/notify`` route body, shared by the publisher's
    announce server and the relay: parse ``after``/``hold`` (and the
    retraction-aware ``after_seq`` watermark), park on the hub, answer
    the current descriptor (200) or nothing-new (204). The hold is
    clamped to the server's ``notify_hold_sec`` so a client cannot pin
    handler threads arbitrarily long.

    Delta-aware push bodies: when the server can look up the CLIENT's
    held version (``manifest_at`` over the history ring), the response
    carries ``changed_chunks`` — the chunk indices that differ from the
    client's watermark version — so a reader with a matching treedef
    token skips the ``/meta`` RTT on sparse bumps. Advisory only: the
    verify-then-swap pipeline runs unchanged on the descriptor itself,
    so a lying hint cannot survive CRC/digest validation."""
    import urllib.parse as _parse

    qs = _parse.parse_qs(query)
    try:
        after = int(qs.get("after", ["-1"])[0])
    except ValueError:
        handler.send_error(400, "bad after watermark")
        return
    after_seq: Optional[int] = None
    if "after_seq" in qs:
        try:
            after_seq = int(qs["after_seq"][0])
        except ValueError:
            after_seq = None
    after_pub = qs.get("after_pub", [None])[0]
    try:
        hold = min(float(qs.get("hold", ["inf"])[0]), notify_hold_sec())
    except ValueError:
        hold = notify_hold_sec()
    metrics.inc("tpuft_serving_notify_requests_total")
    hub.wait_newer(after, hold, after_seq=after_seq)
    latest = descriptor()
    if latest is None or not newer_than_held(latest, after, after_seq, after_pub):
        handler.send_response(204)
        handler.send_header("Content-Length", "0")
        handler.end_headers()
        return
    metrics.inc("tpuft_serving_notify_wakeups_total")
    if manifest_at is not None and after >= 0:
        try:
            changed = changed_chunks_between(manifest_at(after), latest)
        except Exception:  # noqa: BLE001 — the hint must never wound a push
            changed = None
        if changed is not None:
            latest = dict(latest)
            latest["delta_base_step"] = after
            latest["changed_chunks"] = changed
    body = json.dumps(latest).encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    try:
        handler.wfile.write(body)
    except (ConnectionError, TimeoutError, OSError):
        handler.close_connection = True


class PollPacer:
    """Deterministic per-reader poll pacing: full jitter (0.5–1.5× the
    base interval, seeded per reader) plus exponential backoff on
    consecutive failures (capped). Every reader of a tier polling on the
    same cadence is a synchronized thundering herd at each version bump
    — the seed spreads the herd deterministically (reproducible drills),
    and backoff keeps a dead tier from being hammered while it restarts.
    Notify mode makes polling the fallback path; the fallback must not
    herd either."""

    MAX_BACKOFF = 16.0

    def __init__(self, interval: float, seed: Optional[int] = None) -> None:
        self.interval = max(float(interval), 0.01)
        self._rng = random.Random(seed)
        self._mult = 1.0

    def reset(self) -> None:
        self._mult = 1.0

    def next_delay(self, failed: bool = False) -> float:
        """The next sleep: jittered base cadence, doubled (capped) after
        each consecutive ``failed`` round, reset by a clean one."""
        self._mult = min(self._mult * 2.0, self.MAX_BACKOFF) if failed else 1.0
        return self.interval * self._mult * (0.5 + self._rng.random())
