"""Shared wire helpers for the committed-weights serving plane.

The serving plane speaks the heal plane's exact chunk protocol
(checkpointing/http_transport.py: pickled ``/checkpoint/{step}/meta``,
raw ``/checkpoint/{step}/{i}`` chunk bodies, per-chunk CRCs bound into a
whole-checkpoint sha256 digest) plus one JSON announcement route,
``/serving/latest`` — the version descriptor a publisher or relay serves
so readers can discover the newest fully staged version without
unpickling anything. These helpers keep the three roles (publisher /
relay / subscriber) byte-compatible.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from torchft_tpu.checkpointing.http_transport import (
    _CRC_UPDATERS,
    _checkpoint_digest,
)

__all__ = [
    "LATEST_ROUTE",
    "fetch_json",
    "fetch_bytes",
    "latest_descriptor",
    "validate_latest",
    "chunk_crc",
]

LATEST_ROUTE = "/serving/latest"


def fetch_json(url: str, timeout: float) -> Dict[str, Any]:
    """One JSON GET (no retry — serving readers fail over across
    endpoints instead of betting a retry window on one)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        body = resp.read()
    data = json.loads(body)
    if not isinstance(data, dict):
        raise ValueError(f"expected a JSON object from {url}")
    return data


def fetch_bytes(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def latest_descriptor(
    manifest: Dict[str, Any], base: str, published_ts: float
) -> Dict[str, Any]:
    """The ``/serving/latest`` body: the staging manifest
    (http_transport._stage_manifest) plus where to fetch the chunks from
    (``base`` — the publisher's transport/sidecar or a relay) and when
    the version went live."""
    descriptor = dict(manifest)
    descriptor["format"] = 1
    descriptor["base"] = base
    descriptor["published_ts"] = published_ts
    return descriptor


def validate_latest(latest: Dict[str, Any]) -> Optional[str]:
    """Structural + integrity-binding validation of a ``/serving/latest``
    descriptor; returns a rejection reason or None when acceptable. The
    digest MUST be exactly the binding of (step, algo, chunk_crcs) —
    checked before any chunk transfer, so a torn or tampered descriptor
    never costs a payload fetch and can never be adopted."""
    if latest.get("format") != 1:
        return f"unrecognized /serving/latest format: {latest.get('format')!r}"
    for key in ("step", "digest", "crc_algo", "chunk_crcs", "chunk_sizes", "base"):
        if latest.get(key) is None:
            return f"/serving/latest missing {key!r}"
    algo = latest["crc_algo"]
    if algo not in _CRC_UPDATERS:
        return f"descriptor checksums use {algo!r}, unavailable on this host"
    crcs: List[int] = latest["chunk_crcs"]
    sizes: List[int] = latest["chunk_sizes"]
    if len(crcs) != len(sizes) or len(crcs) != int(latest.get("num_chunks", len(crcs))):
        return "descriptor chunk_crcs/chunk_sizes/num_chunks disagree"
    if _checkpoint_digest(int(latest["step"]), algo, crcs) != latest["digest"]:
        return "descriptor digest does not bind its per-chunk checksums"
    return None


def chunk_crc(data: bytes, algo: str) -> int:
    update: Callable[[int, Any], int] = _CRC_UPDATERS[algo]
    return update(0, data)
