"""Committed-weights publisher: the training fleet's publication plane.

A :class:`WeightPublisher` turns every committed step's params into an
immutable, quorum-era-tagged, sha256-digested, per-chunk-CRC'd snapshot —
the exact heal-plane format (checkpointing/http_transport.py format-2
``/meta`` + chunk routes) staged through the existing serve paths, so in
``TPUFT_HEAL_SERVE_MODE=child`` the snapshot is served by the
deprioritized sidecar and publication structurally cannot stall the
donor's step loop (the PR-5 isolation envelope applies unchanged).

Versioned history (torchft_tpu/history.py): the publication transport
keeps the last K staged versions resident (``TPUFT_HISTORY_BYTES`` /
``TPUFT_HISTORY_MAX_VERSIONS``), so besides ``GET /serving/latest``
readers get:

- ``GET /serving/version/{step}`` — a PINNED version's descriptor
  (canary/A-B reads; 410 once retracted, 404 once evicted);
- ``GET /serving/latest-1`` — the previous resident version (the
  standing rollback/canary-baseline alias);
- :meth:`retract_version` — instant fleet-wide model rollback: every
  resident version >= V is dropped (transport chunks AND descriptors)
  and V-1 is re-announced under a HIGHER publication sequence
  (``pub_seq``), so relays and subscribers converge to V-1 while a
  merely-stale endpoint (old pub_seq) still cannot roll anyone back.

Integration contract (see ``Manager.attach_publisher``):

- the manager's commit tails call :meth:`note_commit` — a cheap due-mark,
  never a state sample, so the commit path cannot stall on publication;
- the actual publication runs at the next step boundary on the train
  thread (``Manager._maybe_publish``), lexically AFTER the speculative-
  window drain — analyzer rule R7 pins the ordering exactly like donor
  sends, so speculative-window state is structurally never published;
- a rollback-unwind retracts any due-but-unpublished version through
  :meth:`retract_after` (published versions are post-commit-barrier and
  quorum-final — the belt-and-braces published-history retraction there
  exists for the bounded phantom-commit envelope only, counted in
  ``tpuft_history_retractions_total`` like the operator path).

Readers discover versions via the JSON descriptor routes on
:meth:`address`; chunk traffic never touches the announcement server.
The punisher's ``retract_version`` chaos action arms a file fault at
site ``publisher_retract``: the next :meth:`publish` consumes it and
immediately retracts the just-published version — the rollback-storm
drill's deterministic trigger.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import socket
import threading
import time
import urllib.parse
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from torchft_tpu import metrics, tracing
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.serve_child import (
    UnknownTenantToken,
    tenant_of_authorization,
)
from torchft_tpu.history import DEFAULT_SERVING_VERSIONS, history_max_versions
from torchft_tpu.serving import rollout
from torchft_tpu.serving._wire import (
    LATEST_PREV_ROUTE,
    LATEST_ROUTE,
    NOTIFY_ROUTE,
    VERSION_ROUTE_PREFIX,
    NotifyHub,
    latest_descriptor,
    serve_notify,
)
from torchft_tpu.utils import faultinject, netem

__all__ = [
    "WeightPublisher",
    "ENV_PUBLISH_EVERY",
    "ENV_PUBLISH_CHUNKS",
    "publish_every",
]

ENV_PUBLISH_EVERY = "TPUFT_PUBLISH_EVERY"
ENV_PUBLISH_CHUNKS = "TPUFT_PUBLISH_CHUNKS"

logger = logging.getLogger(__name__)


def publish_every(default: int = 1) -> int:
    """Publication cadence in committed steps (``$TPUFT_PUBLISH_EVERY``,
    default every commit). With a depth-N commit pipeline each publication
    drains the window first, so cadences >= the window depth keep the
    pipeline's RTT hiding between publications."""
    try:
        return max(1, int(os.environ.get(ENV_PUBLISH_EVERY, str(default))))
    except ValueError:
        return default


def _publish_chunks(default: int = 8) -> int:
    try:
        return max(1, int(os.environ.get(ENV_PUBLISH_CHUNKS, str(default))))
    except ValueError:
        return default


class WeightPublisher:
    """Publishes committed params as versioned, integrity-bound snapshots.

    Standalone use (benchmarks, serving-only hosts)::

        pub = WeightPublisher()
        pub.publish(step=1, quorum_id=0, state={"params": params})
        # readers: WeightSubscriber([pub.address()]).poll()

    Training use: ``manager.attach_publisher(pub, lambda: opt.params)`` —
    the manager drives the commit-note -> drain -> publish cycle.
    """

    def __init__(
        self,
        every: Optional[int] = None,
        num_chunks: Optional[int] = None,
        timeout: float = 10.0,
        transport: Optional[HTTPTransport] = None,
        bind_port: int = 0,
        keep_versions: Optional[int] = None,
    ) -> None:
        self._every = every if every is not None else publish_every()
        self._timeout = timeout
        self._owns_transport = transport is None
        keep = history_max_versions(
            keep_versions
            if keep_versions is not None
            else DEFAULT_SERVING_VERSIONS
        )
        self._transport = (
            transport
            if transport is not None
            else HTTPTransport(
                timeout=timeout,
                num_chunks=num_chunks if num_chunks is not None else _publish_chunks(),
                keep_versions=keep,
                # Publication stages speak the serving wire class: encoded
                # with $TPUFT_SERVING_CODEC (default fp32), decoded
                # reader-side after verify-then-swap. Relays are
                # byte-level and fan the encoded chunks out verbatim.
                wire="serving",
            )
        )
        self._lock = threading.Lock()
        self._latest: Optional[Dict[str, Any]] = None
        # Descriptor history, mirroring the transport's resident staged
        # versions: step -> the descriptor announced for it. Pruned to
        # the transport's inventory after every publish, so a descriptor
        # never outlives its chunks.
        self._versions: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._retracted: set = set()
        # Progressive delivery (serving/rollout.py): resident steps still
        # in the canary stream (promotion flips them stable; retraction
        # drops them), and the post-retraction hold — a retracted wave
        # stops tagging new publishes canary until an operator resumes.
        self._canary: set = set()
        self._canary_hold = False
        # A RolloutDirector attaches itself here (director.attach);
        # Manager._maybe_publish drives its per-publish verdict window.
        self.rollout_director: Optional[Any] = None
        # Publication stream identity + sequence: the sequence is
        # monotone over publishes AND retractions; the id scopes it (two
        # publishers' counters are incomparable — readers fall back to
        # step ordering across streams).
        self._pub_id = uuid.uuid4().hex[:12]
        self._pub_seq = 0
        self._due: Optional[int] = None
        self._shutdown = False
        # Long-poll push edge: notify waiters (subscribers, child relays)
        # park here and wake the instant publish() flips the descriptor —
        # propagation becomes a wire RTT, not a poll interval.
        self._hub = NotifyHub()

        publisher = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:
                pass

            def do_GET(self) -> None:
                route, _, query = self.path.partition("?")
                pinned = route.startswith(VERSION_ROUTE_PREFIX)
                if route not in (
                    LATEST_ROUTE,
                    NOTIFY_ROUTE,
                    LATEST_PREV_ROUTE,
                ) and not pinned:
                    self.send_error(404, "unknown route")
                    return
                # Tenant auth parity with the chunk seams: an unknown
                # bearer token is refused at discovery too, so a
                # misconfigured credential surfaces on the FIRST fetch.
                try:
                    tenant = tenant_of_authorization(
                        self.headers.get("Authorization")
                    )
                except UnknownTenantToken as e:
                    metrics.inc("tpuft_serving_auth_rejects_total")
                    self.send_error(401, f"unknown serving tenant: {e}")
                    return
                # Progressive delivery: the tenant's rollout policy (plus
                # an explicit ?stream= request) picks which stream view
                # this discovery request sees — and a request conflicting
                # with the policy is refused here, the 401 discipline's
                # 403 sibling. Inactive policy = the full view, exactly
                # the pre-rollout wire.
                requested = urllib.parse.parse_qs(query).get("stream", [None])[0]
                try:
                    view = rollout.resolve_view(tenant, requested)
                except rollout.WrongStreamError as e:
                    metrics.inc(
                        "tpuft_rollout_wrong_stream_rejects_total", seam="announce"
                    )
                    self.send_error(403, f"wrong rollout stream: {e}")
                    return
                pin_step = rollout.parse_pin(view)
                if route == NOTIFY_ROUTE:
                    serve_notify(
                        self,
                        query,
                        publisher._hub,
                        functools.partial(publisher.latest_for_view, view),
                        manifest_at=publisher.version_descriptor,
                    )
                    return
                if route == LATEST_ROUTE:
                    latest, label = publisher.latest_for_view(view), "latest"
                elif route == LATEST_PREV_ROUTE:
                    latest, label = (
                        publisher.latest_for_view(view, offset=1),
                        "latest-1",
                    )
                else:
                    try:
                        step = int(route[len(VERSION_ROUTE_PREFIX):])
                    except ValueError:
                        self.send_error(400, "bad version step")
                        return
                    if (pin_step is not None and step != pin_step) or (
                        view == rollout.STREAM_STABLE
                        and publisher.stream_of(step) == rollout.STREAM_CANARY
                    ):
                        metrics.inc(
                            "tpuft_rollout_wrong_stream_rejects_total",
                            seam="announce",
                        )
                        self.send_error(
                            403, f"version {step} is outside this tenant's stream"
                        )
                        return
                    if publisher.is_retracted(step):
                        metrics.inc("tpuft_history_retracted_reads_total")
                        self.send_error(410, f"version {step} was retracted")
                        return
                    latest, label = publisher.version_descriptor(step), "version"
                if (
                    latest is None
                    and pin_step is not None
                    and publisher.is_retracted(pin_step)
                ):
                    # A policy-pinned tenant whose pin was retracted gets
                    # the same 410 answer a route-pinned reader gets.
                    metrics.inc("tpuft_history_retracted_reads_total")
                    self.send_error(410, f"version {pin_step} was retracted")
                    return
                if latest is None:
                    self.send_error(404, "no such version published")
                    return
                body = json.dumps(latest).encode()
                metrics.inc("tpuft_serving_requests_total", route=label)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class DualStack(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._server = DualStack(("::", bind_port), Handler)
        self._thread = threading.Thread(
            target=functools.partial(self._server.serve_forever, poll_interval=0.05),
            daemon=True,
            name="tpuft-publish-announce",
        )
        self._thread.start()

    # -- discovery ---------------------------------------------------------

    def address(self) -> str:
        """The announcement endpoint readers poll for ``/serving/latest``."""
        host = socket.gethostname()
        return f"http://{host}:{self._server.server_address[1]}"

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._latest

    def latest_prev(self) -> Optional[Dict[str, Any]]:
        """The previous resident version's descriptor (``latest-1``) —
        the standing canary-baseline / rollback-fallback alias."""
        with self._lock:
            if len(self._versions) < 2:
                return None
            return self._versions[list(self._versions)[-2]]

    def version_descriptor(self, step: int) -> Optional[Dict[str, Any]]:
        """The resident descriptor for pinned ``step`` (None = evicted or
        never published; retraction answers 410 at the route)."""
        with self._lock:
            return self._versions.get(step)

    def latest_for_view(
        self, view: str = rollout.VIEW_ALL, offset: int = 0
    ) -> Optional[Dict[str, Any]]:
        """The newest resident descriptor visible to a rollout ``view``
        (``offset=1`` = that view's latest-1): ``stable`` skips canary
        versions, ``canary``/``all`` see the full stream, ``pin@N`` sees
        exactly N."""
        pin = rollout.parse_pin(view)
        with self._lock:
            if pin is not None:
                return self._versions.get(pin) if offset == 0 else None
            steps = list(self._versions)
            if view == rollout.STREAM_STABLE:
                steps = [s for s in steps if s not in self._canary]
            if len(steps) < offset + 1:
                return None
            return self._versions[steps[-1 - offset]]

    def stream_of(self, step: int) -> str:
        """Which rollout stream resident version ``step`` is in."""
        with self._lock:
            return (
                rollout.STREAM_CANARY
                if step in self._canary
                else rollout.STREAM_STABLE
            )

    def canary_steps(self) -> List[int]:
        with self._lock:
            return sorted(self._canary & set(self._versions))

    def current_canary(self) -> Optional[int]:
        """The newest resident canary step (the verdict loop's subject),
        or None when no canary is live."""
        steps = self.canary_steps()
        return steps[-1] if steps else None

    def set_canary_hold(self, hold: bool) -> None:
        """Pauses (True) / resumes (False) canary tagging of new
        publishes. The director sets the hold after an auto-retraction —
        a failed wave must not immediately re-ship itself; resuming is an
        operator decision."""
        with self._lock:
            self._canary_hold = bool(hold)

    def resident_versions(self) -> List[int]:
        with self._lock:
            return list(self._versions)

    def is_retracted(self, step: int) -> bool:
        with self._lock:
            return step in self._retracted

    # -- manager-facing seams ----------------------------------------------

    @property
    def every(self) -> int:
        return self._every

    def note_commit(self, step: int, quorum_id: int) -> None:
        """Commit-tail hook (runs on whichever thread resolved the vote):
        marks a publication due at the configured cadence. Deliberately
        samples NOTHING — the commit path must never wait on the serving
        plane."""
        if step % self._every == 0:
            with self._lock:
                self._due = step

    def due(self) -> bool:
        with self._lock:
            return self._due is not None

    def retract_after(self, committed_step: int) -> None:
        """Rollback-unwind retraction: drops any due-but-unpublished
        version for a step newer than the unwound-to committed step, so a
        quorum-wide refusal can never surface a version the fleet
        discarded. Published versions are post-barrier (final by quorum
        agreement); the published-history sweep below is belt-and-braces
        for the bounded phantom-commit envelope only — under normal
        operation there is nothing published past the surviving step."""
        with self._lock:
            if self._due is not None and self._due > committed_step:
                self._due = None
                metrics.inc("tpuft_publish_retracted_total")
                tracing.record("publish_retracted", step=committed_step)
            published_newer = [s for s in self._versions if s > committed_step]
        if published_newer:
            self.retract_version(min(published_newer))

    # -- retraction (published history) ------------------------------------

    def retract_version(self, step: int) -> bool:
        """Instant fleet-wide model rollback: retracts published version
        ``step`` AND everything newer (a rollback never leaves a torn
        mix of retracted and post-retracted versions resident), then
        re-announces the newest surviving version (V-1) under a higher
        publication sequence so relays/subscribers converge to it.
        Returns whether anything was actually retracted."""
        with self._lock:
            doomed = sorted(s for s in self._versions if s >= step)
            if not doomed:
                return False
            for s in doomed:
                del self._versions[s]
                self._retracted.add(s)
                self._canary.discard(s)
                metrics.inc("tpuft_history_retractions_total")
            self._pub_seq += 1
            survivor: Optional[Dict[str, Any]] = None
            if self._versions:
                prev_step = list(self._versions)[-1]
                survivor = dict(self._versions[prev_step])
                # Same bytes, same digest — only the publication identity
                # moves: seq-newer so readers adopt it over retracted V,
                # while stale endpoints (old seq) still cannot win.
                survivor["pub_seq"] = self._pub_seq
                survivor["published_ts"] = time.time()
                self._versions[prev_step] = survivor
            self._latest = survivor
            seq = self._pub_seq
        for s in doomed:
            # Chunk bytes leave the serve path too (inline ring and the
            # child's /dev/shm ring): a retracted version 410s at every
            # seam instead of lingering as fetchable bytes.
            self._transport.drop_staged(s, retracted=True)
            tracing.record("version_retracted", step=s)
        logger.warning(
            "retracted published version(s) %s; readers converge to %s",
            doomed,
            survivor["step"] if survivor is not None else "none",
        )
        if survivor is not None:
            self._hub.announce(int(survivor["step"]), seq=seq)
        return True

    def promote_version(self, step: int) -> bool:
        """Promotes canary version ``step`` — and any older resident
        canary, one rollout wave — to the stable stream: the forward
        analogue of :meth:`retract_version`'s survivor re-announce. Same
        bytes, same digest, same era; only the publication identity
        moves (``stream`` flips, ``pub_seq`` bumps), so relays and
        stream-aware readers converge to it through the existing
        seq-ordering gates with zero chunk traffic (every ``(crc,
        size)`` matches — the delta path reuses everything). Returns
        whether anything was actually promoted."""
        with self._lock:
            waved = sorted(
                s for s in self._canary if s <= step and s in self._versions
            )
            if not waved:
                return False
            for s in waved:
                self._canary.discard(s)
                promoted = dict(self._versions[s])
                promoted["stream"] = rollout.STREAM_STABLE
                # Promotion asserts the verdict loop found the wave
                # healthy; a chaos poison marker does not outlive it.
                promoted.pop("poisoned", None)
                self._versions[s] = promoted
            newest = waved[-1]
            self._pub_seq += 1
            announced = dict(self._versions[newest])
            announced["pub_seq"] = self._pub_seq
            announced["published_ts"] = time.time()
            self._versions[newest] = announced
            if self._latest is not None and int(self._latest["step"]) == newest:
                self._latest = announced
            seq = self._pub_seq
        for s in waved:
            self._transport.mark_stream(s, rollout.STREAM_STABLE)
        self._hub.announce(newest, seq=seq)
        metrics.inc("tpuft_rollout_promotions_total")
        tracing.record("canary_promoted", step=newest)
        logger.info("promoted canary version(s) %s to stable", waved)
        return True

    # -- publication -------------------------------------------------------

    def publish(
        self, step: int, quorum_id: Optional[int], state: Any
    ) -> Dict[str, Any]:
        """Stages ``state`` as version ``step`` and flips ``/serving/latest``
        to it. ``state`` must be a committed-only view — when manager-
        attached the call site (``Manager._maybe_publish``) drains the
        speculative window first; standalone callers own that contract.
        jax/numpy leaves are immutable, so holding references is a true
        snapshot; the staging pass makes the one host copy the heal plane
        already budgets for."""
        t0 = time.perf_counter()
        with self._lock:
            self._due = None
        manifest = self._transport.send_checkpoint(
            dst_ranks=[],
            step=step,
            state_dict=state,
            timeout=self._timeout,
            quorum_id=quorum_id,
        )
        if manifest is None:
            raise RuntimeError(
                "WeightPublisher needs a manifest-returning transport "
                "(HTTPTransport); got None from send_checkpoint"
            )
        # Progressive delivery: under an active rollout policy every new
        # publish ships as a CANARY (until the verdict loop promotes it)
        # unless a retraction put the wave on hold. Inactive policy =
        # stream-less descriptors, the exact pre-rollout wire.
        policy = rollout.RolloutPolicy.from_env()
        with self._lock:
            canary_wave = policy.active() and not self._canary_hold
        stream = None
        if policy.active():
            stream = (
                rollout.STREAM_CANARY if canary_wave else rollout.STREAM_STABLE
            )
        poisoned = False
        if canary_wave and faultinject.consume("publisher_canary") == "poison":
            # Chaos seam (punisher ``poison_canary``): the NEXT canary
            # publish carries a synthetic bad-quality marker — CRC-valid
            # bytes, so only the rollout verdict loop reacts; the
            # integrity chain must stay green through the whole drill.
            poisoned = True
            metrics.inc("tpuft_rollout_poisoned_publishes_total")
            logger.warning(
                "punisher poison_canary armed: canary version %d publishes "
                "with synthetic bad-quality evidence",
                step,
            )
        if stream is not None:
            # Mark the chunk seams BEFORE the descriptor flip/announce: a
            # stable tenant must never win a race for canary chunks in
            # the announce window.
            self._transport.mark_stream(step, stream)
        with self._lock:
            self._pub_seq += 1
            latest = latest_descriptor(
                manifest,
                base=self._transport.metadata(),
                published_ts=time.time(),
                depth=0,
                pub_seq=self._pub_seq,
                pub_id=self._pub_id,
                # WAN topology: the root tier's region (None without one) —
                # regional relays use it to order their upstream sets.
                region=netem.local_region(),
                stream=stream,
                poisoned=poisoned,
            )
            self._latest = latest
            self._retracted.discard(step)
            if stream == rollout.STREAM_CANARY:
                self._canary.add(step)
            else:
                self._canary.discard(step)
            self._versions[step] = latest
            if list(self._versions) != sorted(self._versions):
                self._versions = OrderedDict(sorted(self._versions.items()))
            # Descriptors never outlive their chunks: prune to the
            # transport's resident staged inventory.
            resident = set(self._transport.staged_steps()) | {step}
            for s in [s for s in self._versions if s not in resident]:
                del self._versions[s]
            seq = self._pub_seq
        # Wake the long-poll edge AFTER the descriptor flip: a woken
        # waiter always re-reads a fully staged, announced version.
        self._hub.announce(step, seq=seq)
        elapsed = time.perf_counter() - t0
        nbytes = sum(manifest["chunk_sizes"])
        metrics.inc("tpuft_publish_total")
        metrics.inc("tpuft_publish_bytes_total", nbytes)
        metrics.observe("tpuft_publish_stage_seconds", elapsed)
        metrics.set_gauge("tpuft_publish_last_step", step)
        metrics.set_gauge("tpuft_publish_last_time", time.time())
        tracing.record(
            "publish",
            step=step,
            quorum_id=quorum_id,
            bytes=nbytes,
            digest=str(manifest["digest"])[:12],
        )
        # Chaos seam (punisher ``retract_version``): a file-armed
        # retraction is consumed by the publish that follows it — the
        # just-published version is immediately retracted, modeling
        # "canary V shipped and was found bad" deterministically.
        if faultinject.consume("publisher_retract") == "retract":
            logger.warning(
                "punisher retract_version armed: retracting version %d", step
            )
            self.retract_version(step)
        return latest

    def register_error_callback(self, cb: Callable[[Exception], None]) -> None:
        """Serving-sidecar crash funnel, forwarded to the publication
        transport (mirrors the heal transport's contract — the manager
        wires report_error here so a crashed publish sidecar poisons a
        step instead of raising past the boundary)."""
        self._transport.register_error_callback(cb)

    def shutdown(self, wait: bool = True) -> None:
        # Idempotent: the manager's shutdown hook and a direct call may
        # both reach here.
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._hub.close()
        self._server.shutdown()
        self._server.server_close()
        if self._owns_transport:
            self._transport.shutdown(wait=wait)
        if wait:
            self._thread.join(timeout=5)
