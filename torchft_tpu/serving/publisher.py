"""Committed-weights publisher: the training fleet's publication plane.

A :class:`WeightPublisher` turns every committed step's params into an
immutable, quorum-era-tagged, sha256-digested, per-chunk-CRC'd snapshot —
the exact heal-plane format (checkpointing/http_transport.py format-2
``/meta`` + chunk routes) staged through the existing serve paths, so in
``TPUFT_HEAL_SERVE_MODE=child`` the snapshot is served by the
deprioritized sidecar and publication structurally cannot stall the
donor's step loop (the PR-5 isolation envelope applies unchanged).

Integration contract (see ``Manager.attach_publisher``):

- the manager's commit tails call :meth:`note_commit` — a cheap due-mark,
  never a state sample, so the commit path cannot stall on publication;
- the actual publication runs at the next step boundary on the train
  thread (``Manager._maybe_publish``), lexically AFTER the speculative-
  window drain — analyzer rule R7 pins the ordering exactly like donor
  sends, so speculative-window state is structurally never published;
- a rollback-unwind retracts any due-but-unpublished version through
  :meth:`retract_after` (published versions are post-commit-barrier and
  therefore final — the retraction is the invariant's belt-and-braces,
  counted in ``tpuft_publish_retracted_total``).

Readers discover versions via ``GET /serving/latest`` on
:meth:`address` — a JSON descriptor carrying the staged manifest (step,
era, digest, per-chunk CRCs/sizes) plus the chunk base URL (the
transport's inline server or its serving sidecar). Chunk traffic never
touches the announcement server.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from torchft_tpu import metrics, tracing
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.serve_child import (
    UnknownTenantToken,
    tenant_of_authorization,
)
from torchft_tpu.serving._wire import (
    LATEST_ROUTE,
    NOTIFY_ROUTE,
    NotifyHub,
    latest_descriptor,
    serve_notify,
)

__all__ = [
    "WeightPublisher",
    "ENV_PUBLISH_EVERY",
    "ENV_PUBLISH_CHUNKS",
    "publish_every",
]

ENV_PUBLISH_EVERY = "TPUFT_PUBLISH_EVERY"
ENV_PUBLISH_CHUNKS = "TPUFT_PUBLISH_CHUNKS"

logger = logging.getLogger(__name__)


def publish_every(default: int = 1) -> int:
    """Publication cadence in committed steps (``$TPUFT_PUBLISH_EVERY``,
    default every commit). With a depth-N commit pipeline each publication
    drains the window first, so cadences >= the window depth keep the
    pipeline's RTT hiding between publications."""
    try:
        return max(1, int(os.environ.get(ENV_PUBLISH_EVERY, str(default))))
    except ValueError:
        return default


def _publish_chunks(default: int = 8) -> int:
    try:
        return max(1, int(os.environ.get(ENV_PUBLISH_CHUNKS, str(default))))
    except ValueError:
        return default


class WeightPublisher:
    """Publishes committed params as versioned, integrity-bound snapshots.

    Standalone use (benchmarks, serving-only hosts)::

        pub = WeightPublisher()
        pub.publish(step=1, quorum_id=0, state={"params": params})
        # readers: WeightSubscriber([pub.address()]).poll()

    Training use: ``manager.attach_publisher(pub, lambda: opt.params)`` —
    the manager drives the commit-note -> drain -> publish cycle.
    """

    def __init__(
        self,
        every: Optional[int] = None,
        num_chunks: Optional[int] = None,
        timeout: float = 10.0,
        transport: Optional[HTTPTransport] = None,
        bind_port: int = 0,
    ) -> None:
        self._every = every if every is not None else publish_every()
        self._timeout = timeout
        self._owns_transport = transport is None
        self._transport = (
            transport
            if transport is not None
            else HTTPTransport(
                timeout=timeout,
                num_chunks=num_chunks if num_chunks is not None else _publish_chunks(),
            )
        )
        self._lock = threading.Lock()
        self._latest: Optional[Dict[str, Any]] = None
        self._due: Optional[int] = None
        self._shutdown = False
        # Long-poll push edge: notify waiters (subscribers, child relays)
        # park here and wake the instant publish() flips the descriptor —
        # propagation becomes a wire RTT, not a poll interval.
        self._hub = NotifyHub()

        publisher = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:
                pass

            def do_GET(self) -> None:
                route, _, query = self.path.partition("?")
                if route not in (LATEST_ROUTE, NOTIFY_ROUTE):
                    self.send_error(404, "unknown route")
                    return
                # Tenant auth parity with the chunk seams: an unknown
                # bearer token is refused at discovery too, so a
                # misconfigured credential surfaces on the FIRST fetch.
                try:
                    tenant_of_authorization(self.headers.get("Authorization"))
                except UnknownTenantToken as e:
                    metrics.inc("tpuft_serving_auth_rejects_total")
                    self.send_error(401, f"unknown serving tenant: {e}")
                    return
                if route == NOTIFY_ROUTE:
                    serve_notify(self, query, publisher._hub, publisher.latest)
                    return
                with publisher._lock:
                    latest = publisher._latest
                if latest is None:
                    self.send_error(404, "nothing published yet")
                    return
                body = json.dumps(latest).encode()
                metrics.inc("tpuft_serving_requests_total", route="latest")
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class DualStack(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._server = DualStack(("::", bind_port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name="tpuft-publish-announce",
        )
        self._thread.start()

    # -- discovery ---------------------------------------------------------

    def address(self) -> str:
        """The announcement endpoint readers poll for ``/serving/latest``."""
        host = socket.gethostname()
        return f"http://{host}:{self._server.server_address[1]}"

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._latest

    # -- manager-facing seams ----------------------------------------------

    @property
    def every(self) -> int:
        return self._every

    def note_commit(self, step: int, quorum_id: int) -> None:
        """Commit-tail hook (runs on whichever thread resolved the vote):
        marks a publication due at the configured cadence. Deliberately
        samples NOTHING — the commit path must never wait on the serving
        plane."""
        if step % self._every == 0:
            with self._lock:
                self._due = step

    def due(self) -> bool:
        with self._lock:
            return self._due is not None

    def retract_after(self, committed_step: int) -> None:
        """Rollback-unwind retraction: drops any due-but-unpublished
        version for a step newer than the unwound-to committed step, so a
        quorum-wide refusal can never surface a version the fleet
        discarded. Versions already published are post-barrier (final by
        quorum agreement) and are never retracted."""
        with self._lock:
            if self._due is not None and self._due > committed_step:
                self._due = None
                metrics.inc("tpuft_publish_retracted_total")
                tracing.record("publish_retracted", step=committed_step)

    # -- publication -------------------------------------------------------

    def publish(
        self, step: int, quorum_id: Optional[int], state: Any
    ) -> Dict[str, Any]:
        """Stages ``state`` as version ``step`` and flips ``/serving/latest``
        to it. ``state`` must be a committed-only view — when manager-
        attached the call site (``Manager._maybe_publish``) drains the
        speculative window first; standalone callers own that contract.
        jax/numpy leaves are immutable, so holding references is a true
        snapshot; the staging pass makes the one host copy the heal plane
        already budgets for."""
        t0 = time.perf_counter()
        with self._lock:
            self._due = None
        manifest = self._transport.send_checkpoint(
            dst_ranks=[],
            step=step,
            state_dict=state,
            timeout=self._timeout,
            quorum_id=quorum_id,
        )
        if manifest is None:
            raise RuntimeError(
                "WeightPublisher needs a manifest-returning transport "
                "(HTTPTransport); got None from send_checkpoint"
            )
        latest = latest_descriptor(
            manifest,
            base=self._transport.metadata(),
            published_ts=time.time(),
            depth=0,
        )
        with self._lock:
            self._latest = latest
        # Wake the long-poll edge AFTER the descriptor flip: a woken
        # waiter always re-reads a fully staged, announced version.
        self._hub.announce(step)
        elapsed = time.perf_counter() - t0
        nbytes = sum(manifest["chunk_sizes"])
        metrics.inc("tpuft_publish_total")
        metrics.inc("tpuft_publish_bytes_total", nbytes)
        metrics.observe("tpuft_publish_stage_seconds", elapsed)
        metrics.set_gauge("tpuft_publish_last_step", step)
        metrics.set_gauge("tpuft_publish_last_time", time.time())
        tracing.record(
            "publish",
            step=step,
            quorum_id=quorum_id,
            bytes=nbytes,
            digest=str(manifest["digest"])[:12],
        )
        return latest

    def register_error_callback(self, cb: Callable[[Exception], None]) -> None:
        """Serving-sidecar crash funnel, forwarded to the publication
        transport (mirrors the heal transport's contract — the manager
        wires report_error here so a crashed publish sidecar poisons a
        step instead of raising past the boundary)."""
        self._transport.register_error_callback(cb)

    def shutdown(self, wait: bool = True) -> None:
        # Idempotent: the manager's shutdown hook and a direct call may
        # both reach here.
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._hub.close()
        self._server.shutdown()
        self._server.server_close()
        if self._owns_transport:
            self._transport.shutdown(wait=wait)
        if wait:
            self._thread.join(timeout=5)
