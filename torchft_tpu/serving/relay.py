"""Caching fan-out relay for the committed-weights serving plane.

A :class:`CachingRelay` sits between the training fleet's publishers and
a reader population: it polls the upstreams' ``/serving/latest``, pulls
each new version into an in-memory chunk cache, and serves the same
protocol back out — so relays stack (a relay's upstream can be another
relay) and readers hammer relay RAM instead of the training fleet.

Resilience properties (the heal plane's, applied to serving):

- **Atomic versions**: a pulled version becomes visible only after EVERY
  chunk verified against the descriptor's CRCs and the descriptor's
  digest verified as the binding of those CRCs — readers can never
  observe a torn or half-pulled version.
- **Delta-aware pulls**: chunks whose ``(crc, size)`` matches the cached
  previous version are reused without fetching (the delta-rejoin match,
  PR-8), so steady-state version bumps move only changed bytes
  (``tpuft_serving_delta_bytes_saved_total``).
- **Upstream failover mid-pull**: the chunk fetch walks every upstream
  currently announcing the same digest (committed state is bitwise
  identical across the fleet — the striped-heal argument); an upstream
  that dies mid-pull is fenced and its chunks re-fetched from survivors.
  All upstreams dead aborts the pull and keeps serving the last good
  version — degradation is staleness, never unavailability or
  corruption.
- **Era fencing**: a descriptor whose quorum era regresses below the
  held version's is rejected (a stale survivor cannot roll readers
  back); chunk GETs accept the same ``?quorum_id`` tag the heal plane
  uses and answer 409 on a mismatch.
- **Chaos seam**: the punisher's ``kill_relay`` fault (site
  ``serving_relay[:port]``) is consumed at the poll loop and the serve
  handler; ``die()`` drops the process abruptly mid-service, the drill
  asserting readers fail over without ever observing a bad version.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from torchft_tpu import metrics, tracing
from torchft_tpu._safe_pickle import safe_loads
from torchft_tpu.checkpointing.serve_child import (
    UnknownTenantToken,
    maybe_pace_serve,
    tenant_of_authorization,
)
from torchft_tpu.history import DEFAULT_SERVING_VERSIONS, StagedVersionStore
from torchft_tpu.serving._wire import (
    LATEST_PREV_ROUTE,
    LATEST_ROUTE,
    NOTIFY_ROUTE,
    VERSION_ROUTE_PREFIX,
    CancelScope,
    NotifyHub,
    PollPacer,
    chunk_crc,
    fetch_bytes,
    fetch_json,
    fetch_notify,
    latest_descriptor,
    newer_than_held,
    notify_enabled,
    same_stream,
    serve_notify,
    validate_latest,
)
from torchft_tpu.serving import rollout
from torchft_tpu.utils import faultinject, netem

__all__ = ["CachingRelay", "ENV_SERVING_POLL_SEC", "serving_poll_sec"]

ENV_SERVING_POLL_SEC = "TPUFT_SERVING_POLL_SEC"
# WAN topology: the region this serving node advertises on its
# descriptors (readers/child relays prefer same-region tiers). Falls back
# to the netem topology map's view of this process.
ENV_SERVING_REGION = "TPUFT_SERVING_REGION"

logger = logging.getLogger(__name__)


def serving_poll_sec(default: float = 0.5) -> float:
    """Upstream poll cadence (``$TPUFT_SERVING_POLL_SEC``)."""
    try:
        return max(0.01, float(os.environ.get(ENV_SERVING_POLL_SEC, str(default))))
    except ValueError:
        return default


class _RelayVersion:
    """One fully verified, immutable cached version."""

    __slots__ = (
        "step",
        "quorum_id",
        "digest",
        "crc_algo",
        "chunk_crcs",
        "chunk_sizes",
        "meta_bytes",
        "chunks",
        "ts",
        "depth",
        "origin_ts",
        "pub_seq",
        "pub_id",
        "tree_token",
        "chunk_codecs",
        "stream",
        "poisoned",
    )

    def __init__(
        self,
        step: int,
        quorum_id: Optional[int],
        digest: str,
        crc_algo: str,
        chunk_crcs: List[int],
        chunk_sizes: List[int],
        meta_bytes: bytes,
        chunks: List[bytes],
        ts: float,
        depth: int = 1,
        origin_ts: Optional[float] = None,
        pub_seq: Optional[int] = None,
        pub_id: Optional[str] = None,
        tree_token: Optional[str] = None,
        chunk_codecs: Optional[List[str]] = None,
        stream: Optional[str] = None,
        poisoned: bool = False,
    ) -> None:
        self.step = step
        self.quorum_id = quorum_id
        self.digest = digest
        self.crc_algo = crc_algo
        self.chunk_crcs = chunk_crcs
        self.chunk_sizes = chunk_sizes
        self.meta_bytes = meta_bytes
        self.chunks = chunks
        self.ts = ts
        # Tree position: upstream's announced depth + 1 (publisher = 0).
        self.depth = depth
        # ORIGIN publication time, preserved across tiers — the
        # publish-to-edge propagation reference.
        self.origin_ts = origin_ts if origin_ts is not None else ts
        # Origin publication stream identity + sequence (retraction
        # ordering) and the treedef token (readers' /meta-skip key) —
        # all preserved verbatim across tiers.
        self.pub_seq = pub_seq
        self.pub_id = pub_id
        self.tree_token = tree_token
        # Quantized wire plane: the chunk bytes this relay caches are
        # whatever the publisher staged — possibly codec-encoded. The
        # tags ride the tree verbatim (they are digest-bound; the relay
        # itself never decodes).
        self.chunk_codecs = chunk_codecs
        # Progressive delivery (serving/rollout.py): the origin stream
        # tag ("canary"/"stable"; None = pre-rollout publisher) and the
        # punisher's poison marker — publication-plane metadata like
        # pub_seq, preserved verbatim across tiers, never part of the
        # digest/CRC integrity binding.
        self.stream = stream
        self.poisoned = poisoned

    def manifest(self) -> Dict[str, Any]:
        manifest: Dict[str, Any] = {
            "step": self.step,
            "quorum_id": self.quorum_id,
            "crc_algo": self.crc_algo,
            "chunk_crcs": self.chunk_crcs,
            "chunk_sizes": self.chunk_sizes,
            "num_chunks": len(self.chunk_crcs),
            "digest": self.digest,
            "tree_token": self.tree_token,
        }
        if self.chunk_codecs:
            manifest["chunk_codecs"] = list(self.chunk_codecs)
            manifest["codec"] = self.chunk_codecs[0]
        return manifest


class _PullFailed(RuntimeError):
    """This pull attempt failed (every source fenced); the relay keeps
    serving its current version and retries next poll round."""


class CachingRelay:
    """Pulls committed-weight versions from upstream publishers/relays and
    fans them out to readers from an in-memory chunk cache."""

    def __init__(
        self,
        upstreams: List[str],
        poll_interval: Optional[float] = None,
        timeout: float = 10.0,
        bind_port: int = 0,
        start: bool = True,
        notify: Optional[bool] = None,
        token: Optional[str] = None,
        jitter_seed: Optional[int] = None,
        region: Optional[str] = None,
    ) -> None:
        if not upstreams:
            raise ValueError("CachingRelay needs at least one upstream")
        self._upstreams = list(upstreams)
        # WAN topology: the region this tier serves FROM (advertised on
        # descriptors) — explicit ctor arg > $TPUFT_SERVING_REGION > the
        # netem topology map. Upstream regions are LEARNED from their
        # descriptors during discovery; same-region upstreams are then
        # preferred (stable order otherwise) so the root→regional-edge
        # link is crossed once per version, not once per reader.
        env_region = os.environ.get(ENV_SERVING_REGION, "").strip()
        self._region = (region or env_region or netem.local_region() or None)
        if self._region is not None:
            self._region = self._region.lower()
        self._upstream_regions: Dict[str, Optional[str]] = {}
        self._timeout = timeout
        self._poll_interval = (
            poll_interval if poll_interval is not None else serving_poll_sec()
        )
        self._notify = notify if notify is not None else notify_enabled()
        # Bearer token this relay presents upstream (it pulls on its
        # tenant's behalf; its OWN readers present their own tokens).
        self._token = token
        self._jitter_seed = jitter_seed
        self._lock = threading.Lock()
        self._current: Optional[_RelayVersion] = None
        # Resident version ring (torchft_tpu/history.py): the last K
        # adopted versions stay servable from relay RAM — pinned
        # (/serving/version/{step}) and latest-1 reads at the edge, and
        # the retraction path's V-1 fallback without a re-pull.
        self._versions = StagedVersionStore(
            max_versions=DEFAULT_SERVING_VERSIONS, ring="relay"
        )
        self._stop = threading.Event()
        # Aborts the poll thread's parked upstream notify GET at shutdown
        # (the server-side hold can be ~25 s; a teardown must not wait it out).
        self._notify_cancel = CancelScope()
        self.dead = False
        # Downstream long-poll edge: subscribers/child relays park here.
        self._hub = NotifyHub()
        metrics.set_gauge("tpuft_serving_relay_upstreams", len(self._upstreams))

        relay = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:
                pass

            def do_GET(self) -> None:
                if relay._consume_fault():
                    # kill_relay armed: die mid-service, connection cut.
                    self.close_connection = True
                    relay.die()
                    return
                if metrics._serve_metrics_http(self, metrics.REGISTRY, self.path):
                    return
                split = urllib.parse.urlsplit(self.path)
                # Multi-tenant identity of this reader (None = tokenless,
                # pooled under the default tenant); unknown tokens are
                # refused before any body (or notify hold) is spent.
                try:
                    tenant = tenant_of_authorization(
                        self.headers.get("Authorization")
                    )
                except UnknownTenantToken as e:
                    metrics.inc("tpuft_serving_auth_rejects_total")
                    self.send_error(401, f"unknown serving tenant: {e}")
                    return
                # Progressive-delivery view (serving/rollout.py): the
                # ``?stream=`` request resolved against this tenant's
                # rollout policy — tokenless readers pool under the
                # default tenant at these DESCRIPTOR seams, and a request
                # the policy does not cover is refused 403 before any
                # body (the PR-12 401 discipline).
                policy = rollout.RolloutPolicy.from_env()
                requested = urllib.parse.parse_qs(split.query).get(
                    "stream", [None]
                )[0]
                try:
                    view = rollout.resolve_view(tenant, requested, policy)
                except rollout.WrongStreamError as e:
                    metrics.inc(
                        "tpuft_rollout_wrong_stream_rejects_total", seam="relay"
                    )
                    self.send_error(403, str(e))
                    return
                pin_step = rollout.parse_pin(view)
                version = relay._latest_for_view(view)
                if split.path == NOTIFY_ROUTE:
                    serve_notify(
                        self,
                        split.query,
                        relay._hub,
                        functools.partial(relay._descriptor_for_view, view),
                        manifest_at=relay._manifest_at,
                    )
                    return
                if split.path in (LATEST_ROUTE, LATEST_PREV_ROUTE) or (
                    split.path.startswith(VERSION_ROUTE_PREFIX)
                ):
                    if split.path == LATEST_ROUTE:
                        label = "latest"
                    elif split.path == LATEST_PREV_ROUTE:
                        label = "latest-1"
                        version = relay._latest_for_view(view, offset=1)
                    else:
                        label = "version"
                        try:
                            want = int(split.path[len(VERSION_ROUTE_PREFIX):])
                        except ValueError:
                            self.send_error(400, "bad version step")
                            return
                        version = relay._version_for(want)
                        if (pin_step is not None and want != pin_step) or (
                            view == rollout.STREAM_STABLE
                            and version is not None
                            and (version.stream or rollout.STREAM_STABLE)
                            == rollout.STREAM_CANARY
                        ):
                            metrics.inc(
                                "tpuft_rollout_wrong_stream_rejects_total",
                                seam="relay",
                            )
                            self.send_error(
                                403,
                                f"version {want} is outside this tenant's "
                                "rollout stream",
                            )
                            return
                        if relay._versions.is_retracted(want):
                            metrics.inc("tpuft_history_retracted_reads_total")
                            self.send_error(
                                410, f"version {want} was retracted"
                            )
                            return
                    if version is None:
                        self.send_error(404, "no such version cached")
                        return
                    body = json.dumps(relay._descriptor(version)).encode()
                    metrics.inc("tpuft_serving_requests_total", route=label)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    if label == "latest" and policy.is_shadow(tenant):
                        # Shadow tee: STRICTLY after the stable response
                        # is on the wire — a tee failure is a counter,
                        # never a slow or failed stable read.
                        relay._shadow_tee(version)
                    return
                parts = split.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown route")
                    return
                try:
                    step = int(parts[1])
                except ValueError:
                    self.send_error(400, "bad step")
                    return
                if version is None or version.step != step:
                    # Pinned/lagging readers: chunk bytes for any RESIDENT
                    # ring version are servable, not just the newest.
                    version = relay._version_for(step)
                if version is None:
                    if relay._versions.is_retracted(step):
                        metrics.inc("tpuft_history_retracted_reads_total")
                        self.send_error(410, f"version {step} was retracted")
                        return
                    # No waiting: a reader racing a version bump retries
                    # its poll against the new descriptor instead of
                    # parking a relay thread.
                    self.send_error(404, f"step {step} not cached")
                    return
                want_era = urllib.parse.parse_qs(split.query).get("quorum_id")
                if (
                    want_era
                    and version.quorum_id is not None
                    and str(version.quorum_id) != want_era[0]
                ):
                    self.send_error(
                        409,
                        f"stale quorum era: cached {version.quorum_id}, "
                        f"reader wants {want_era[0]}",
                    )
                    return
                # Wrong-stream chunk gate: a KNOWN tenant outside this
                # version's stream is refused; tokenless fetches (child
                # relays, heal-plane pulls) are never gated here.
                if tenant is not None:
                    deny = rollout.wrong_stream_chunk_reason(
                        tenant, step, version.stream, policy
                    )
                    if deny is not None:
                        metrics.inc(
                            "tpuft_rollout_wrong_stream_rejects_total",
                            seam="relay",
                        )
                        self.send_error(403, deny)
                        return
                if parts[2] == "meta":
                    body = version.meta_bytes
                    route = "meta"
                else:
                    try:
                        body = version.chunks[int(parts[2])]
                    except (ValueError, IndexError):
                        self.send_error(400, "bad chunk index")
                        return
                    route = "chunk"
                metrics.inc("tpuft_serving_requests_total", route=route)
                metrics.inc("tpuft_serving_bytes_total", len(body))
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                out = maybe_pace_serve(self.wfile, cls="serving", tenant=tenant)
                try:
                    out.write(body)
                except (ConnectionError, TimeoutError, OSError):
                    self.close_connection = True

        class DualStack(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            daemon_threads = True

        self._server = DualStack(("::", bind_port), Handler)
        self._serve_thread = threading.Thread(
            target=functools.partial(self._server.serve_forever, poll_interval=0.05), daemon=True, name="tpuft-relay-http"
        )
        self._serve_thread.start()
        self._poll_thread: Optional[threading.Thread] = None
        if start:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True, name="tpuft-relay-poll"
            )
            self._poll_thread.start()

    # -- surface -----------------------------------------------------------

    def address(self) -> str:
        host = socket.gethostname()
        return f"http://{host}:{self._server.server_address[1]}"

    def current(self) -> Optional[_RelayVersion]:
        with self._lock:
            return self._current

    def latest_prev(self) -> Optional[_RelayVersion]:
        """The previous resident ring version (``latest-1``)."""
        steps = self._versions.latest_steps(2)
        if len(steps) < 2:
            return None
        payload = self._versions.get(steps[1])
        return payload if isinstance(payload, _RelayVersion) else None

    def _version_for(self, step: int) -> Optional[_RelayVersion]:
        """A resident ring version for exactly ``step`` (pinned reads and
        lagging chunk fetches), or None."""
        current = self.current()
        if current is not None and current.step == step:
            return current
        payload = self._versions.get(step)
        return payload if isinstance(payload, _RelayVersion) else None

    def _manifest_at(self, step: int) -> Optional[Dict[str, Any]]:
        """Manifest lookup for the delta-aware notify body (the changed-
        chunk set vs a parked client's held version)."""
        version = self._version_for(step)
        return version.manifest() if version is not None else None

    def _descriptor(
        self, version: Optional[_RelayVersion] = None
    ) -> Optional[Dict[str, Any]]:
        """The ``/serving/latest`` body for ``version`` (default: the
        held one): this relay's address as the chunk base, its tree
        depth, the preserved origin publication time, and the origin
        publication stream identity/sequence (retraction ordering rides
        the tree unchanged)."""
        version = version if version is not None else self.current()
        if version is None:
            return None
        return latest_descriptor(
            version.manifest(),
            base=self.address(),
            published_ts=version.ts,
            depth=version.depth,
            origin_ts=version.origin_ts,
            pub_seq=version.pub_seq,
            pub_id=version.pub_id,
            region=self._region,
            stream=version.stream,
            poisoned=version.poisoned,
        )

    # -- progressive delivery (serving/rollout.py) --------------------------

    def _stream_versions(self, want: str) -> List[_RelayVersion]:
        """Resident versions of stream ``want``, newest first. Untagged
        versions (a pre-rollout publisher) count as stable — the exact
        degenerate behavior."""
        seen: Dict[int, _RelayVersion] = {}
        current = self.current()
        if current is not None:
            seen[current.step] = current
        for step in self._versions.latest_steps(DEFAULT_SERVING_VERSIONS):
            payload = self._versions.get(step)
            if isinstance(payload, _RelayVersion):
                seen.setdefault(step, payload)
        return [
            v
            for _, v in sorted(seen.items(), reverse=True)
            if (v.stream or rollout.STREAM_STABLE) == want
        ]

    def _latest_for_view(
        self, view: str = rollout.VIEW_ALL, offset: int = 0
    ) -> Optional[_RelayVersion]:
        """The newest resident version a ``view`` may observe (``offset``
        = 1 is the view's latest-1). Pin views see exactly their pinned
        step; the stable view filters canary-tagged versions out; canary
        and full views see the newest overall (canary cohorts read the
        stable baseline too — latest-1 comparisons)."""
        pin = rollout.parse_pin(view)
        if pin is not None:
            return self._version_for(pin) if offset == 0 else None
        if view == rollout.STREAM_STABLE:
            stable = self._stream_versions(rollout.STREAM_STABLE)
            return stable[offset] if len(stable) > offset else None
        return self.current() if offset == 0 else self.latest_prev()

    def _descriptor_for_view(
        self, view: str = rollout.VIEW_ALL
    ) -> Optional[Dict[str, Any]]:
        version = self._latest_for_view(view)
        # None must stay None: _descriptor(None) falls back to current(),
        # which would leak the full view to a filtered one.
        return self._descriptor(version) if version is not None else None

    def _shadow_tee(self, stable: Optional[_RelayVersion]) -> None:
        """Shadow read: verify the resident canary version through the
        full integrity pipeline (every chunk CRC + the meta/digest
        binding + the poison marker) WITHOUT serving it, and report
        divergence vs the stable version the shadow tenant was actually
        answered from. Every attempt is a counted observation — the
        verdict loop's evidence — and every failure is a counter, never
        an error on the stable path (the publish-failure-only-makes-
        readers-stale invariant, extended)."""
        canary = None
        try:
            versions = self._stream_versions(rollout.STREAM_CANARY)
            canary = versions[0] if versions else None
            if canary is None:
                return  # no live canary: nothing to observe
            metrics.inc("tpuft_rollout_shadow_reads_total")
            for i, data in enumerate(canary.chunks):
                if len(data) != canary.chunk_sizes[i]:
                    raise ValueError(f"shadow canary chunk {i} size mismatch")
                if chunk_crc(data, canary.crc_algo) != canary.chunk_crcs[i]:
                    raise ValueError(f"shadow canary chunk {i} checksum mismatch")
            meta = safe_loads(canary.meta_bytes)
            if not isinstance(meta, dict) or meta.get("digest") != canary.digest:
                raise ValueError("shadow canary meta does not bind its digest")
            if canary.poisoned:
                # The punisher's CRC-valid bad-quality marker: integrity
                # holds, quality does not — exactly what only a shadow
                # read (never a stable reader) is allowed to observe.
                raise ValueError("shadow canary carries the poisoned marker")
            divergence = None
            if (
                stable is not None
                and stable.crc_algo == canary.crc_algo
                and len(stable.chunk_crcs) == len(canary.chunk_crcs)
            ):
                changed = sum(
                    1
                    for a, b in zip(stable.chunk_crcs, canary.chunk_crcs)
                    if a != b
                )
                divergence = changed / max(len(canary.chunk_crcs), 1)
                metrics.set_gauge("tpuft_rollout_shadow_divergence", divergence)
            tracing.record(
                "shadow_divergence",
                step=canary.step,
                stable_step=stable.step if stable is not None else -1,
                divergence=divergence if divergence is not None else -1.0,
            )
        except Exception as e:  # noqa: BLE001 — shadow failures are evidence
            metrics.inc("tpuft_rollout_shadow_failures_total")
            logger.warning(
                "shadow read of canary step %s failed: %s",
                canary.step if canary is not None else "?",
                e,
            )

    def _ordered_upstreams(self) -> List[str]:
        """The upstream set, same-region tiers first (stable within each
        class, so the configured order still breaks ties). Region-less
        relays — or upstreams that never advertised one — keep the exact
        configured order; preference can only reorder, never drop."""
        if self._region is None:
            return list(self._upstreams)
        return sorted(
            self._upstreams,
            key=lambda u: 0 if self._upstream_regions.get(u) == self._region else 1,
        )

    def _consume_fault(self) -> bool:
        return (
            faultinject.consume(
                f"serving_relay:{self._server.server_address[1]}"
            )
            == "die"
        )

    def die(self) -> None:
        """Chaos seam (punisher ``kill_relay``): drop abruptly — server
        closed under live readers, poll loop stopped, cache gone. Readers
        observe connection errors and fail over; they can never observe a
        bad version (there is nothing to serve torn)."""
        if self.dead:
            return
        self.dead = True
        self._stop.set()
        # Wake parked notify waiters so their hanging GETs resolve now
        # (204 / connection cut) instead of at hold expiry.
        self._hub.close()
        metrics.inc("tpuft_serving_relay_deaths_total")
        tracing.record("relay_died", step=self._current.step if self._current else -1)
        logger.warning("relay %s dying (kill_relay)", self.address())
        threading.Thread(
            target=self._server.shutdown, daemon=True, name="tpuft-relay-die"
        ).start()
        self._server.server_close()

    # -- pulling -----------------------------------------------------------

    def _poll_loop(self) -> None:
        # Deterministic per-relay jitter: seeded by the bound port so a
        # tier of relays spreads its poll herd reproducibly.
        pacer = PollPacer(
            self._poll_interval,
            seed=self._jitter_seed
            if self._jitter_seed is not None
            else self._server.server_address[1],
        )
        pending: Optional[Dict[str, Any]] = None
        while not self._stop.is_set():
            failed = False
            try:
                self.poll_once(descriptor=pending)
            except Exception as e:  # noqa: BLE001 — keep serving, retry next round
                failed = True
                metrics.inc("tpuft_serving_pull_failures_total")
                logger.warning("relay pull failed (%s); retrying next round", e)
            pending = None
            if not failed and self._notify and not self.dead:
                cur = self.current()
                # after=-1 before the first adoption: an upstream that has
                # (or gets) ANY version wakes us — tree bring-up rides the
                # push edge too, tier by tier.
                outcome = self._wait_notify(
                    cur.step if cur is not None else -1,
                    after_seq=cur.pub_seq if cur is not None else None,
                    after_pub=cur.pub_id if cur is not None else None,
                )
                if outcome is not None:
                    # Long-poll round completed: an upstream pushed a new
                    # descriptor (loop pulls it NOW — the ~RTT/hop
                    # propagation path) or the hold expired and we re-arm;
                    # no poll-interval sleep either way.
                    if isinstance(outcome, dict):
                        pending = outcome
                    continue
            if self._stop.wait(pacer.next_delay(failed)):
                return

    def _wait_notify(
        self,
        after: int,
        after_seq: Optional[int] = None,
        after_pub: Optional[str] = None,
    ) -> Any:
        """One long-poll round against the upstream set: parks on the
        first upstream that speaks ``/serving/notify`` until it announces
        a version newer than ``after`` (returns its descriptor — the
        loop pulls from the announcer without rediscovery), its bounded
        hold expires (False — re-arm), or every upstream failed (None —
        the caller falls back to the jittered poll cadence; a tier that
        cannot push degrades to polling, never to silence)."""
        for upstream in self._ordered_upstreams():
            if self._stop.is_set():
                return False
            try:
                # Tokenless tiers park on the full-stream view (canary
                # versions must propagate down the tree); a token-scoped
                # relay parks on its tenant's own policy view.
                woke = fetch_notify(
                    upstream, after, self._timeout, token=self._token,
                    after_seq=after_seq, after_pub=after_pub,
                    cancel=self._notify_cancel,
                    stream=rollout.VIEW_ALL if self._token is None else None,
                )
            except Exception:  # noqa: BLE001 — old/dead upstream: next one
                metrics.inc("tpuft_serving_upstream_failovers_total")
                continue
            return woke if woke is not None else False
        return None

    def poll_once(self, descriptor: Optional[Dict[str, Any]] = None) -> bool:
        """One poll round: discover the newest acceptable upstream version
        and pull it if it is new. Returns True when a new version was
        adopted. ``descriptor`` (a just-delivered, still-unvalidated
        notify body) skips the discovery fan-out — the pull fetches from
        its announcer directly, which is what makes push propagation cost
        ~(1.5 + chunks) RTTs per hop instead of re-walking every
        upstream; a mid-pull failure falls back to the next full
        discovery round, so the failover set is narrower only for the
        fast path, never for recovery."""
        if self._consume_fault():
            self.die()
            return False
        if self.dead:
            return False
        best: Optional[Dict[str, Any]] = None
        sources: List[str] = []
        if descriptor is not None:
            reason = validate_latest(descriptor)
            if reason is not None:
                metrics.inc("tpuft_serving_integrity_rejects_total")
                logger.warning("notify descriptor rejected: %s", reason)
                return False
            best = descriptor
        else:
            # Tokenless tiers discover the FULL stream (?stream=all — the
            # infra view, never policy-gated) so canary versions ride the
            # tree; a token-scoped relay discovers its tenant's own view.
            view_qs = f"?stream={rollout.VIEW_ALL}" if self._token is None else ""
            for upstream in self._ordered_upstreams():
                try:
                    latest = fetch_json(
                        f"{upstream}{LATEST_ROUTE}{view_qs}",
                        self._timeout,
                        token=self._token,
                    )
                except Exception:  # noqa: BLE001 — a dead upstream is routine
                    metrics.inc("tpuft_serving_upstream_failovers_total")
                    continue
                reason = validate_latest(latest)
                if reason is not None:
                    metrics.inc("tpuft_serving_integrity_rejects_total")
                    logger.warning("upstream %s rejected: %s", upstream, reason)
                    continue
                # Learn this tier's advertised region for the next round's
                # nearest-tier ordering (advisory routing metadata only).
                self._upstream_regions[upstream] = latest.get("region")
                if best is None or _newer(latest, best):
                    best = latest
            if best is None:
                return False
            # Every upstream announcing the SAME digest serves
            # interchangeable bytes (committed state is bitwise
            # identical) — they form this pull's failover set, same-region
            # sources first so failover crosses regions only when the
            # near tier is gone.
            for upstream in self._ordered_upstreams():
                try:
                    latest = fetch_json(
                        f"{upstream}{LATEST_ROUTE}{view_qs}",
                        self._timeout,
                        token=self._token,
                    )
                except Exception:  # noqa: BLE001
                    continue
                if latest.get("digest") == best["digest"] and latest.get("base"):
                    sources.append(latest["base"])
        if best is None:
            return False
        current = self.current()
        if current is not None:
            if (
                best["step"] == current.step
                and best["digest"] == current.digest
                and best.get("pub_seq") in (None, current.pub_seq)
            ):
                return False
            stream = same_stream(best, current.pub_seq, current.pub_id)
            retraction = False
            if stream:
                # Same publication stream: seq ordering governs, and a
                # seq-newer descriptor at a LOWER step is a sanctioned
                # retraction (adopted below, converging this tier — and
                # everything downstream — to V-1). Its era is V-1's own,
                # exempt from the forward-motion fence.
                if not newer_than_held(
                    best, current.step, current.pub_seq, current.pub_id
                ):
                    return False
                retraction = int(best["step"]) < current.step
            if not retraction:
                if (
                    best.get("quorum_id") is not None
                    and current.quorum_id is not None
                    and best["quorum_id"] < current.quorum_id
                ):
                    # A stale-era survivor must never roll readers back.
                    metrics.inc("tpuft_serving_stale_era_rejects_total")
                    return False
                if not stream and best["step"] <= current.step:
                    return False
        self._pull(best, sources or [best["base"]], previous=current)
        return True

    def _pull(
        self,
        latest: Dict[str, Any],
        sources: List[str],
        previous: Optional[_RelayVersion],
    ) -> None:
        step = int(latest["step"])
        algo: str = latest["crc_algo"]
        crcs: List[int] = [int(c) for c in latest["chunk_crcs"]]
        sizes: List[int] = [int(s) for s in latest["chunk_sizes"]]
        t0 = time.perf_counter()
        live = list(dict.fromkeys(sources))
        meta_bytes = self._fetch_failover(
            live, f"/checkpoint/{step}/meta", expect_crc=None, algo=algo
        )
        # Bind the fetched meta to the validated descriptor BEFORE it can
        # be cached or re-served: a stale/corrupt upstream must produce a
        # counted pull failure (readers stay on the previous version),
        # never poisoned relay state — the subscriber's torn-read fence,
        # applied at the tier that would otherwise amplify the bad bytes.
        # tpuft_check rule R9 (verify-before-adopt) pins this path.
        meta = safe_loads(meta_bytes)
        if (
            not isinstance(meta, dict)
            or meta.get("step") != step
            or meta.get("digest") != latest["digest"]
        ):
            metrics.inc("tpuft_serving_meta_digest_rejects_total")
            raise _PullFailed(
                f"meta for step {step} does not match the validated "
                "descriptor digest (torn read or corrupt upstream)"
            )
        depth = int(latest.get("depth", 0)) + 1
        chunks: List[Optional[bytes]] = [None] * len(crcs)
        reused = 0
        saved = 0
        fetched = 0
        delta_ok = (
            previous is not None
            and previous.crc_algo == algo
            and len(previous.chunk_crcs) == len(crcs)
        )
        for i in range(len(crcs)):
            if (
                delta_ok
                and previous.chunk_crcs[i] == crcs[i]  # type: ignore[union-attr]
                and previous.chunk_sizes[i] == sizes[i]  # type: ignore[union-attr]
            ):
                # Serialized (crc, size) equality implies byte-equal
                # chunks — the PR-8 delta-rejoin argument; reuse the
                # cached bytes instead of refetching.
                chunks[i] = previous.chunks[i]  # type: ignore[union-attr]
                reused += 1
                saved += sizes[i]
                continue
            data = self._fetch_failover(
                live, f"/checkpoint/{step}/{i}", expect_crc=crcs[i], algo=algo,
                expect_size=sizes[i],
            )
            chunks[i] = data
            fetched += len(data)
        version = _RelayVersion(
            step=step,
            quorum_id=latest.get("quorum_id"),
            digest=latest["digest"],
            crc_algo=algo,
            chunk_crcs=crcs,
            chunk_sizes=sizes,
            meta_bytes=meta_bytes,
            chunks=chunks,  # type: ignore[arg-type]
            ts=time.time(),
            depth=depth,
            origin_ts=latest.get("origin_ts"),
            pub_seq=latest.get("pub_seq"),
            pub_id=latest.get("pub_id"),
            tree_token=latest.get("tree_token"),
            chunk_codecs=latest.get("chunk_codecs"),
            stream=latest.get("stream"),
            poisoned=bool(latest.get("poisoned")),
        )
        # Strictly LOWER step = retraction; a seq-newer re-announce at
        # the SAME step is a canary PROMOTION (the stream tag flipped to
        # stable) and must not drop ring versions.
        retraction = previous is not None and step < previous.step
        with self._lock:
            self._current = version
        self._versions.put(step, version, sum(sizes))
        if retraction:
            # A sanctioned rollback (seq-newer at a lower step — the
            # ordering gate upstream already proved it): resident ring
            # versions past the survivor are dropped, so this tier serves
            # no retracted version to pinned readers either — converged,
            # never a torn mix.
            self._versions.drop_newer(step, retracted=True)
            metrics.inc("tpuft_serving_retraction_adoptions_total")
            tracing.record("version_retracted", step=previous.step, survivor=step)
        # Swap first, THEN wake the long-poll edge: a woken waiter always
        # reads the fully verified version.
        self._hub.announce(step, seq=latest.get("pub_seq"))
        metrics.inc("tpuft_serving_pulls_total")
        # WAN accounting: a pull whose source tier advertised a different
        # region crossed the expensive link — the evidence that the
        # root→regional edge is crossed once per version, not per reader.
        src_region = latest.get("region")
        if self._region is not None and src_region is not None:
            if src_region != self._region:
                metrics.inc("tpuft_wan_serving_cross_region_pulls_total")
            else:
                metrics.inc("tpuft_wan_serving_same_region_pulls_total")
        if reused:
            metrics.inc("tpuft_serving_delta_chunks_reused_total", reused)
            metrics.inc("tpuft_serving_delta_bytes_saved_total", saved)
        metrics.set_gauge("tpuft_serving_version_step", step)
        metrics.set_gauge("tpuft_serving_relay_depth", depth)
        tracing.record(
            "serving_pull",
            step=step,
            quorum_id=latest.get("quorum_id"),
            fetched_bytes=fetched,
            reused_chunks=reused,
            bytes_saved=saved,
            duration_s=round(time.perf_counter() - t0, 6),
        )

    def _fetch_failover(
        self,
        live: List[str],
        route: str,
        expect_crc: Optional[int],
        algo: str,
        expect_size: Optional[int] = None,
    ) -> bytes:
        """Fetches ``route`` from the first live source that serves valid
        bytes; a source that fails (dead, corrupt, truncated) is fenced
        from THIS pull and the fetch fails over — the striped-heal
        reassignment shape, sized for a relay (fences mutate ``live`` in
        place so later chunks skip the dead source up front)."""
        while live:
            base = live[0]
            try:
                data = fetch_bytes(f"{base}{route}", self._timeout)
                if expect_size is not None and len(data) != expect_size:
                    raise ValueError(
                        f"short read: {len(data)} != {expect_size} bytes"
                    )
                if expect_crc is not None and chunk_crc(data, algo) != expect_crc:
                    raise ValueError("chunk checksum mismatch")
                # Round-robin across the survivors so a multi-upstream
                # pull spreads load like a striped heal.
                live.append(live.pop(0))
                return data
            except Exception as e:  # noqa: BLE001 — fence and fail over
                live.pop(0)
                metrics.inc("tpuft_serving_upstream_failovers_total")
                logger.warning(
                    "relay fetch %s from %s failed (%s); %d source(s) left",
                    route,
                    base,
                    e,
                    len(live),
                )
        raise _PullFailed(f"every source failed for {route}")

    def shutdown(self, wait: bool = True) -> None:
        self._stop.set()
        self._notify_cancel.close()
        self._hub.close()
        if not self.dead:
            self._server.shutdown()
            self._server.server_close()
        if wait:
            self._serve_thread.join(timeout=5)
            if self._poll_thread is not None:
                self._poll_thread.join(timeout=5)


def _newer(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    """Version ordering across candidate descriptors: same publication
    stream orders by sequence (a retraction outranks the retracted step),
    else quorum era first (never prefer a stale-era survivor), then
    step."""
    if (
        a.get("pub_id") is not None
        and a.get("pub_id") == b.get("pub_id")
        and a.get("pub_seq") is not None
        and b.get("pub_seq") is not None
    ):
        return int(a["pub_seq"]) > int(b["pub_seq"])
    era_a = a.get("quorum_id")
    era_b = b.get("quorum_id")
    if era_a is not None and era_b is not None and era_a != era_b:
        return era_a > era_b
    return int(a["step"]) > int(b["step"])
