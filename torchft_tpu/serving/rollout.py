"""Progressive delivery for the committed-weights serving plane.

Three pieces, composing the primitives the serving plane already has
(per-tenant bearer identity, pinned versions, sanctioned ``pub_seq``
retraction, hysteresis verdict discipline) into canary rollouts:

- **Per-tenant version policies** (:class:`RolloutPolicy`): a tenant
  resolves — as a PURE FUNCTION, the ``zero.shard_assignment`` spirit:
  never negotiated, identical in every process — to a stream:
  ``stable``, ``canary``, or ``pin@<step>``. Explicit entries come from
  ``$TPUFT_ROLLOUT_POLICY`` (``tenant:stream`` pairs, ``*`` as the
  default); unlisted tenants fall into the canary cohort by a
  sha256-derived percent bucket (``$TPUFT_ROLLOUT_CANARY_PERCENT``) —
  sha256, NOT Python's salted ``hash()``, so the same token lands in the
  same cohort in every process and every run, with exact percent
  boundaries. The policy is enforced at every serving seam (publisher
  announce, relay, inline transport, serve-child IN-child — the PR-12
  401 discipline, now answering 403 on a wrong-stream request) and
  tokenless readers pool under the ``default`` tenant at descriptor
  seams, exactly like the egress-fairness plane. Tokenless CHUNK
  fetches stay ungated: they are the heal plane and relay-tree pulls,
  which must see every stream.

- **Shadow reads**: a relay tees a shadow tenant's discovery fetches to
  the resident canary version and verifies it through the full
  CRC/digest pipeline WITHOUT serving it — the shadow tenant is always
  answered from the stable view, the tee runs strictly after the stable
  response is written, and every tee failure is a counted observation
  (``tpuft_rollout_shadow_failures_total``), never an error on the
  stable path: the publish-failure-only-makes-readers-stale invariant,
  extended to the canary plane.

- **The rollout verdict loop** (:class:`RolloutEvaluator` +
  :class:`RolloutDirector`): health.py's HealthScorer discipline applied
  to model VERSIONS — a window is "bad" only when the canary failure
  rate clears BOTH a multiplicative threshold against the stable
  baseline AND an absolute gap floor; K consecutive bad windows latch a
  ``retract`` verdict, K consecutive healthy windows a ``promote``
  verdict, one opposing window resets the streak — a transient blip can
  never retract. Windows with insufficient canary evidence are REFUSED
  (counted), never judged. Actuation happens at exactly one seam
  (:meth:`RolloutDirector._actuate`), through the existing
  ``retract_version`` / ``promote_version`` paths, and
  ``$TPUFT_ROLLOUT_MODE=alert`` turns the loop advisory: verdicts latch
  and count, nothing actuates.

Canary descriptors ride the existing ``pub_seq``/``pub_id`` +
digest/CRC/era verify-then-swap chain unchanged — the ``stream`` tag is
publication-plane metadata like ``pub_seq`` (announce-chain routing,
never part of the integrity binding), so a wrong-stream or torn adoption
stays structurally impossible: stream refusal happens server-side at
every seam AND reader-side before the verification pipeline even runs.

This module is deliberately jax-free (the serve child imports it).
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

# Dual-context import (the serve_child discipline): in-process this is the
# normal package import; file-loaded into the jax-free serve child (where
# ``__package__`` is empty and importing torchft_tpu would drag in jax) it
# reuses the child's already-file-loaded metrics module.
if __package__ and __package__.startswith("torchft_tpu"):
    from torchft_tpu import metrics
else:  # pragma: no cover - exercised only inside the spawned serve child
    import importlib.util as _ilu
    import sys as _sys
    from pathlib import Path as _Path

    metrics = _sys.modules.get("tpuft_serve_metrics")
    if metrics is None:
        _spec = _ilu.spec_from_file_location(
            "tpuft_serve_metrics", _Path(__file__).resolve().parent.parent / "metrics.py"
        )
        assert _spec is not None and _spec.loader is not None
        metrics = _ilu.module_from_spec(_spec)
        _sys.modules["tpuft_serve_metrics"] = metrics
        _spec.loader.exec_module(metrics)

__all__ = [
    "ENV_POLICY",
    "ENV_CANARY_PERCENT",
    "ENV_SHADOW_TENANTS",
    "ENV_MODE",
    "ENV_THRESHOLD",
    "ENV_WINDOWS",
    "ENV_MIN_GAP",
    "ENV_MIN_SAMPLES",
    "STREAM_STABLE",
    "STREAM_CANARY",
    "VIEW_ALL",
    "WrongStreamError",
    "cohort_bucket",
    "in_canary_cohort",
    "parse_policy",
    "parse_pin",
    "RolloutPolicy",
    "resolve_view",
    "wrong_stream_chunk_reason",
    "RolloutEvaluator",
    "RolloutDirector",
    "STATE_CODES",
]

ENV_POLICY = "TPUFT_ROLLOUT_POLICY"
ENV_CANARY_PERCENT = "TPUFT_ROLLOUT_CANARY_PERCENT"
ENV_SHADOW_TENANTS = "TPUFT_ROLLOUT_SHADOW_TENANTS"
ENV_MODE = "TPUFT_ROLLOUT_MODE"
ENV_THRESHOLD = "TPUFT_ROLLOUT_THRESHOLD"
ENV_WINDOWS = "TPUFT_ROLLOUT_WINDOWS"
ENV_MIN_GAP = "TPUFT_ROLLOUT_MIN_GAP"
ENV_MIN_SAMPLES = "TPUFT_ROLLOUT_MIN_SAMPLES"

STREAM_STABLE = "stable"
STREAM_CANARY = "canary"
# The "shadow" policy token: the tenant is SERVED stable; its discovery
# fetches additionally tee a canary verification at the relay.
_STREAM_SHADOW = "shadow"
# The infra view: full-stream discovery (relay-tree pulls) — never a
# tenant policy value, only a requested ``?stream=all`` view.
VIEW_ALL = "all"

# fleet_status / the tpuft_rollout_state gauge: verdict-loop posture.
STATE_CODES = {
    "idle": 0,  # no live canary (rollout inactive or between waves)
    "watch": 1,  # canary live, evidence healthy so far
    "suspect": 2,  # bad streak open, below the K-window latch
    "retracted": 3,  # last verdict retracted the canary
    "promoted": 4,  # last verdict promoted the canary
}


class WrongStreamError(Exception):
    """A request conflicts with the requesting tenant's rollout policy
    (403 at the seam — the stream analogue of UnknownTenantToken's 401)."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# cohort assignment: a pure function of the tenant name
# ---------------------------------------------------------------------------


def cohort_bucket(tenant: Optional[str]) -> int:
    """Deterministic bucket in [0, 10000) for ``tenant`` (tokenless
    readers pool under ``default``, mirroring the egress-fairness plane).
    sha256-derived — bitwise identical across processes, machines, and
    runs (Python's ``hash()`` is per-process salted and MUST NOT be used
    here) — so cohort membership is a pure function, never negotiated:
    the ``zero.shard_assignment`` discipline applied to readers."""
    name = tenant if tenant else "default"
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:8], "big") % 10000


def in_canary_cohort(tenant: Optional[str], percent: float) -> bool:
    """Whether ``tenant`` falls in a ``percent``-sized canary cohort.
    The boundary is exact: ``percent`` maps to ``round(percent * 100)``
    buckets of the 10000, so 12.34% admits buckets [0, 1234) — no float
    drift at the edge."""
    return cohort_bucket(tenant) < int(round(max(0.0, min(100.0, percent)) * 100))


# ---------------------------------------------------------------------------
# policy table
# ---------------------------------------------------------------------------


def parse_pin(stream: str) -> Optional[int]:
    """The pinned step of a ``pin@<step>`` stream token, else None."""
    if stream.startswith("pin@"):
        try:
            return int(stream[4:])
        except ValueError:
            return None
    return None


def parse_policy(raw: Optional[str] = None) -> Tuple[Dict[str, str], List[str]]:
    """Parses ``$TPUFT_ROLLOUT_POLICY`` (``tenant:stream`` pairs,
    comma-separated; ``*`` = the default for unlisted tenants; stream in
    {stable, canary, shadow, pin@<step>}). Malformed entries are skipped
    and returned in the error list (the serving_tenant_tokens
    discipline: a typo degrades one entry, never the table) — doctor's
    rollout probe surfaces them as WARN."""
    raw = os.environ.get(ENV_POLICY, "") if raw is None else raw
    entries: Dict[str, str] = {}
    errors: List[str] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        tenant, sep, stream = part.partition(":")
        tenant, stream = tenant.strip(), stream.strip().lower()
        if not sep or not tenant or not stream:
            errors.append(f"malformed policy entry {part!r} (want tenant:stream)")
            continue
        if stream not in (STREAM_STABLE, STREAM_CANARY, _STREAM_SHADOW):
            if parse_pin(stream) is None:
                errors.append(
                    f"policy entry {part!r}: unknown stream {stream!r} "
                    "(want stable|canary|shadow|pin@<step>)"
                )
                continue
        entries[tenant] = stream
    return entries, errors


def _canary_percent(raw: Optional[str] = None) -> float:
    raw = os.environ.get(ENV_CANARY_PERCENT, "0") if raw is None else raw
    try:
        return max(0.0, min(100.0, float(raw)))
    except ValueError:
        return 0.0


def _shadow_tenants(raw: Optional[str] = None) -> FrozenSet[str]:
    raw = os.environ.get(ENV_SHADOW_TENANTS, "") if raw is None else raw
    return frozenset(t.strip() for t in raw.split(",") if t.strip())


class RolloutPolicy:
    """An immutable resolved snapshot of the rollout policy: explicit
    entries > ``*`` default > percent cohort > stable. Re-read from the
    environment at each seam (``from_env``) so every process — donors,
    relays, the serve child — resolves identically from the same
    fleet-wide env agreement, with zero shared state."""

    def __init__(
        self,
        entries: Optional[Dict[str, str]] = None,
        percent: Optional[float] = None,
        shadows: Optional[FrozenSet[str]] = None,
        errors: Optional[List[str]] = None,
    ) -> None:
        self.entries = dict(entries or {})
        self.percent = _canary_percent(None if percent is None else str(percent))
        self.shadows = frozenset(shadows or ())
        self.errors = list(errors or ())

    @classmethod
    def from_env(cls) -> "RolloutPolicy":
        entries, errors = parse_policy()
        return cls(
            entries=entries,
            percent=_canary_percent(),
            shadows=_shadow_tenants(),
            errors=errors,
        )

    def active(self) -> bool:
        """Whether the rollout plane is configured at all. False is the
        degenerate case: every seam behaves exactly as before this plane
        existed (full-view descriptors, no stream gating)."""
        return bool(self.entries) or self.percent > 0 or bool(self.shadows)

    def is_shadow(self, tenant: Optional[str]) -> bool:
        name = tenant if tenant else "default"
        return name in self.shadows or self.entries.get(name) == _STREAM_SHADOW

    def resolve(self, tenant: Optional[str]) -> str:
        """The stream ``tenant`` reads: ``stable``, ``canary``, or
        ``pin@<step>``. Shadow tenants resolve STABLE — their canary
        exposure is the relay tee, never their served bytes."""
        name = tenant if tenant else "default"
        entry = self.entries.get(name)
        if entry is None:
            entry = self.entries.get("*")
        if entry == _STREAM_SHADOW:
            return STREAM_STABLE
        if entry is not None:
            return entry
        if self.percent > 0 and in_canary_cohort(name, self.percent):
            return STREAM_CANARY
        return STREAM_STABLE


def resolve_view(
    tenant: Optional[str],
    requested: Optional[str],
    policy: Optional[RolloutPolicy] = None,
) -> str:
    """The descriptor view a discovery request gets: ``all`` (full
    stream — relay-tree pulls), ``stable``, ``canary``, or ``pin@N``.

    ``requested`` is the explicit ``?stream=`` query value; the tenant's
    policy caps it — a stable/pinned tenant asking for the canary (or
    full) view is a wrong-stream request and raises
    :class:`WrongStreamError` (403 at the seam, counted). With the
    rollout plane unconfigured every request resolves to ``all``: the
    exact pre-rollout wire behavior."""
    policy = policy if policy is not None else RolloutPolicy.from_env()
    if not policy.active():
        return VIEW_ALL
    # Tokenless infra pulls (relay-tree discovery/notify) request the
    # full-stream view explicitly; like tokenless chunk fetches they are
    # never policy-gated — a relay must see every stream to serve its
    # mixed reader population.
    if tenant is None and requested == VIEW_ALL:
        return VIEW_ALL
    stream = policy.resolve(tenant)
    pin = parse_pin(stream)
    if pin is not None:
        if requested is not None and requested != stream:
            raise WrongStreamError(
                f"tenant is pinned to version {pin}; requested {requested!r}"
            )
        return stream
    if stream == STREAM_STABLE:
        if requested in (STREAM_CANARY, VIEW_ALL):
            raise WrongStreamError(
                f"tenant reads the stable stream; requested {requested!r}"
            )
        return STREAM_STABLE
    # Canary-cohort tenants may read any view (the stable baseline
    # included — latest-1 comparisons).
    return requested if requested is not None else STREAM_CANARY


def wrong_stream_chunk_reason(
    tenant: Optional[str],
    step: int,
    step_stream: Optional[str],
    policy: Optional[RolloutPolicy] = None,
) -> Optional[str]:
    """Chunk-seam enforcement: the refusal reason (403) when ``tenant``
    must not read version ``step`` whose stream tag is ``step_stream``,
    else None. Tokenless fetches are NEVER gated here — they are the
    heal plane and relay-tree pulls, which must see every stream (the
    caller applies the default-tenant pooling only at descriptor
    seams)."""
    if tenant is None:
        return None
    policy = policy if policy is not None else RolloutPolicy.from_env()
    if not policy.active():
        return None
    stream = policy.resolve(tenant)
    pin = parse_pin(stream)
    if pin is not None:
        if step != pin:
            return f"tenant is pinned to version {pin}, not {step}"
        return None
    if stream == STREAM_STABLE and step_stream == STREAM_CANARY:
        return f"version {step} is a canary; tenant reads the stable stream"
    return None


# ---------------------------------------------------------------------------
# verdict loop
# ---------------------------------------------------------------------------


class RolloutEvaluator:
    """Pure verdict logic over per-window canary evidence — the
    HealthScorer discipline applied to model versions. No I/O, no
    threads; the director owns plumbing, unit tests drive this directly.

    A window is "bad" only when the canary failure rate clears BOTH
    bounds against the stable baseline: ``canary_rate > threshold *
    stable_rate`` (multiplicative — a uniformly failing fleet never
    blames its canary) AND ``canary_rate - stable_rate > min_gap`` (the
    absolute floor — 3x a per-mille noise rate is not a verdict).
    ``consecutive`` bad windows latch ``retract``; ``consecutive``
    healthy (judgeable, not bad) windows latch ``promote``; one opposing
    window resets the streak — a transient blip can never retract. A
    window with fewer than ``min_samples`` canary observations is
    REFUSED (``tpuft_rollout_verdicts_refused_total``): streaks do not
    advance on evidence that is not there."""

    def __init__(
        self,
        threshold: Optional[float] = None,
        consecutive: Optional[int] = None,
        min_samples: Optional[int] = None,
        min_gap: Optional[float] = None,
    ) -> None:
        self.threshold = max(
            1.01,
            threshold if threshold is not None else _env_float(ENV_THRESHOLD, 3.0),
        )
        self.consecutive = max(
            1,
            consecutive if consecutive is not None else _env_int(ENV_WINDOWS, 3),
        )
        self.min_samples = max(
            1,
            min_samples if min_samples is not None else _env_int(ENV_MIN_SAMPLES, 1),
        )
        self.min_gap = max(
            0.0, min_gap if min_gap is not None else _env_float(ENV_MIN_GAP, 0.05)
        )
        self.bad_streak = 0
        self.good_streak = 0
        self.refusals = 0

    def reset(self) -> None:
        """A new canary wave starts its evidence from zero."""
        self.bad_streak = 0
        self.good_streak = 0

    def observe_window(
        self,
        canary_reads: int,
        canary_failures: int,
        stable_reads: int = 0,
        stable_failures: int = 0,
        divergence: Optional[float] = None,
    ) -> Dict[str, Any]:
        """One evidence window. Returns the verdict dict; hysteresis
        state advances only on judgeable windows."""
        verdict: Dict[str, Any] = {
            "judgeable": False,
            "bad": False,
            "action": None,
            "bad_streak": self.bad_streak,
            "good_streak": self.good_streak,
            "canary_rate": None,
            "stable_rate": None,
            "divergence": divergence,
        }
        if canary_reads < self.min_samples:
            self.refusals += 1
            metrics.inc("tpuft_rollout_verdicts_refused_total")
            return verdict
        canary_rate = canary_failures / max(canary_reads, 1)
        stable_rate = (
            stable_failures / max(stable_reads, 1) if stable_reads > 0 else 0.0
        )
        bad = (
            canary_rate > self.threshold * max(stable_rate, 1e-9)
            and (canary_rate - stable_rate) > self.min_gap
        )
        if bad:
            self.bad_streak += 1
            self.good_streak = 0
        else:
            self.good_streak += 1
            self.bad_streak = 0
        action = None
        if self.bad_streak >= self.consecutive:
            action = "retract"
        elif self.good_streak >= self.consecutive:
            action = "promote"
        verdict.update(
            judgeable=True,
            bad=bad,
            action=action,
            bad_streak=self.bad_streak,
            good_streak=self.good_streak,
            canary_rate=round(canary_rate, 6),
            stable_rate=round(stable_rate, 6),
        )
        return verdict


class RolloutDirector:
    """Drives the verdict loop against one publisher: collects a
    per-commit evidence window (process-local ``tpuft_rollout_shadow_*``
    counter deltas — relay tees land there — plus its own cheap canary
    self-probe of the publisher's resident descriptor), feeds the
    evaluator, and actuates the latched verdict at EXACTLY one seam
    (:meth:`_actuate`): ``publisher.retract_version`` for a bad canary
    (the sanctioned pub_seq rollback every tier already follows),
    ``publisher.promote_version`` for a surviving one. A retraction also
    holds further canary tagging (``publisher.set_canary_hold``) — the
    wave is over until an operator resumes it. ``mode="alert"``
    (``$TPUFT_ROLLOUT_MODE``) suppresses actuation: verdicts latch,
    count, and trace, nothing moves.

    Fleet deployments that scrape counters centrally can bypass the
    process-local collection and feed :meth:`RolloutEvaluator
    .observe_window` directly; the actuation seam is unchanged."""

    _WINDOW_COUNTERS = (
        "tpuft_rollout_shadow_reads_total",
        "tpuft_rollout_shadow_failures_total",
    )

    def __init__(
        self,
        publisher: Any = None,
        evaluator: Optional[RolloutEvaluator] = None,
        mode: Optional[str] = None,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self.evaluator = evaluator if evaluator is not None else RolloutEvaluator()
        raw_mode = (
            mode if mode is not None else os.environ.get(ENV_MODE, "actuate")
        )
        self.mode = "alert" if str(raw_mode).strip().lower() == "alert" else "actuate"
        self.state = "idle"
        self._wall = wall
        self._publisher = None
        self._last: Dict[str, float] = {}
        self._watched: Optional[int] = None
        if publisher is not None:
            self.attach(publisher)

    def attach(self, publisher: Any) -> None:
        self._publisher = publisher
        publisher.rollout_director = self

    # -- evidence ------------------------------------------------------------

    def _counter_deltas(self) -> Dict[str, int]:
        deltas: Dict[str, int] = {}
        for name in self._WINDOW_COUNTERS:
            total = metrics.counter_total(name)
            deltas[name] = int(total - self._last.get(name, 0.0))
            self._last[name] = total
        return deltas

    def _self_probe(self, canary_steps: Sequence[int]) -> Tuple[int, int]:
        """One cheap in-process observation of EVERY resident canary in
        the wave per window: each descriptor must exist, validate, and
        carry no poison marker. No network, no payload — it guarantees
        every window has at least one sample per wave member so an
        unread canary still converges to a verdict instead of starving
        on refusals, and a bad wave member stays visible after younger
        healthy canaries join the wave."""
        from torchft_tpu.serving._wire import validate_latest

        reads = 0
        failures = 0
        for step in canary_steps:
            reads += 1
            descriptor = self._publisher.version_descriptor(step)
            if descriptor is None:
                failures += 1
            elif (
                validate_latest(descriptor) is not None
                or descriptor.get("poisoned")
            ):
                failures += 1
        return reads, failures

    # -- the loop ------------------------------------------------------------

    def on_commit(self, step: int, quorum_id: Optional[int] = None) -> None:
        """Manager step-boundary hook (``Manager._maybe_publish`` tail):
        one evidence window per committed step — windows keep elapsing
        between publishes so a live canary wave converges to a verdict
        regardless of the publish cadence. Never raises — the train loop
        must not pay for a verdict bug."""
        try:
            self.tick()
        except Exception:  # noqa: BLE001 — verdicts are advisory to the step loop
            import logging

            logging.getLogger(__name__).warning(
                "rollout verdict tick failed", exc_info=True
            )

    def tick(self) -> Optional[Dict[str, Any]]:
        """One verdict window; returns the evaluator's verdict (None when
        no canary is live)."""
        pub = self._publisher
        if pub is None:
            return None
        steps = sorted(pub.canary_steps())
        if not steps:
            if self.state not in ("retracted", "promoted"):
                self.state = "idle"
            self._watched = None
            self._emit_gauges(-1)
            return None
        # The wave identity is the OLDEST resident canary step: later
        # canary publishes JOIN the wave (a publish-every-commit cadence
        # must not reset the evaluator each window or verdicts starve);
        # only a genuinely new wave — after a promote/retract emptied the
        # set — gets fresh hysteresis and fresh counters.
        wave = steps[0]
        canary = steps[-1]
        if wave != self._watched:
            self.evaluator.reset()
            self._counter_deltas()
            self._watched = wave
            self.state = "watch"
        deltas = self._counter_deltas()
        probe_reads, probe_failures = self._self_probe(steps)
        verdict = self.evaluator.observe_window(
            canary_reads=deltas["tpuft_rollout_shadow_reads_total"] + probe_reads,
            canary_failures=(
                deltas["tpuft_rollout_shadow_failures_total"] + probe_failures
            ),
            divergence=metrics.gauge_value("tpuft_rollout_shadow_divergence"),
        )
        if verdict["judgeable"]:
            self.state = "suspect" if verdict["bad_streak"] > 0 else "watch"
        if verdict["action"] is not None:
            metrics.inc("tpuft_rollout_verdicts_total", action=verdict["action"])
            self._actuate(verdict["action"], canary, verdict)
        self._emit_gauges(canary if self._watched is not None else -1)
        return verdict

    def _emit_gauges(self, canary_step: int) -> None:
        metrics.set_gauge("tpuft_rollout_state", STATE_CODES[self.state])
        metrics.set_gauge("tpuft_rollout_canary_step", canary_step)
        metrics.set_gauge(
            "tpuft_rollout_canary_percent", RolloutPolicy.from_env().percent
        )

    # -- actuation: exactly one seam ----------------------------------------

    def _actuate(self, action: str, canary_step: int, verdict: Dict[str, Any]) -> None:
        from torchft_tpu import tracing

        if self.mode != "actuate":
            metrics.inc("tpuft_rollout_alert_suppressed_total")
            tracing.record(
                "rollout_alert",
                step=canary_step,
                action=action,
                bad_streak=verdict["bad_streak"],
            )
            self.evaluator.reset()
            return
        if action == "retract":
            self._publisher.set_canary_hold(True)
            oldest = min(self._publisher.canary_steps(), default=canary_step)
            retracted = self._publisher.retract_version(oldest)
            if retracted:
                metrics.inc("tpuft_rollout_retractions_total")
            tracing.record(
                "canary_retracted",
                step=canary_step,
                bad_streak=verdict["bad_streak"],
                canary_rate=verdict["canary_rate"],
            )
            self.state = "retracted"
        else:
            self._publisher.promote_version(canary_step)
            self.state = "promoted"
        self._watched = None
        self.evaluator.reset()
