"""Reader client for the committed-weights serving plane.

A :class:`WeightSubscriber` polls one or more serving endpoints (relays
or publishers — they speak the same protocol) and atomically swaps to
the newest *fully verified* version:

- the ``/serving/latest`` descriptor must bind its digest to its
  per-chunk CRCs (checked before any transfer);
- the pickled ``/meta`` must carry the SAME digest (the torn-read fence:
  a version bump between the descriptor fetch and the meta fetch changes
  the digest, aborting this poll instead of mixing versions) — UNLESS
  the descriptor's ``tree_token`` matches the reader's cached treedef,
  in which case the ``/meta`` RTT is skipped entirely on sparse bumps
  (``tpuft_serving_meta_fetches_skipped_total``): every adopted chunk
  still verifies against the descriptor's digest-bound CRCs, so the
  fence moves, it never weakens;
- every chunk verifies against its CRC and size before decode;
- only then does :meth:`current` flip to the new
  :class:`ServingVersion` — a reader can never observe a torn, partially
  adopted, or corrupt version, and a failed poll leaves the held version
  untouched.

Era discipline: a descriptor whose quorum era regresses below the held
version's is a stale-era read and is rejected
(``tpuft_serving_stale_era_rejects_total``). Version ordering is the
publication sequence (``pub_seq``) when both sides carry one — which is
how a deliberate RETRACTION (step decreases, seq increases) converges
readers to V-1 (``tpuft_serving_retraction_adoptions_total``) while a
stale endpoint still cannot roll anyone back — and step order against
pre-history servers.

Pinned reads (the history ring's read surface): construct with
``pin=<step>`` to follow exactly one version via
``/serving/version/{step}`` (adoption REFUSES any other step —
``tpuft_serving_wrong_version_rejects_total``; a 410 marks the pin
retracted, see :attr:`pin_retracted`), or ``pin="latest-1"`` to trail
the newest version by one (canary baseline).

Delta-aware: decoded chunks are cached per index with their ``(crc,
size)``; a version bump re-decodes (and re-fetches) only chunks that
actually changed — including across SKIPPED versions (a reader that
held V-2 moves only the chunks that changed since V-2;
``tpuft_history_delta_chain_hops_total`` counts the crossed versions).

Push-aware: :meth:`WeightSubscriber.wait_for_update` parks a long-poll
``/serving/notify`` request at an endpoint (bounded hold, see
_wire.fetch_notify) and polls the moment a newer version is announced —
adoption latency becomes a wire RTT, not a poll interval. The delivered
descriptor is never trusted: the identical verify-then-swap pipeline
runs on every adoption, push or poll (its advisory ``changed_chunks``
body can save a fetch, never corrupt one). :meth:`watch` is the reader
loop (notify-first, deterministic-jittered poll with exponential backoff
as the fallback — the fallback path must not thundering-herd either).

Multi-tenant: a reader constructed with a bearer ``token`` sends it on
every serving fetch; the serve seams charge its bytes to its tenant's
egress sub-bucket (TPUFT_SERVING_TENANT_TOKENS / _GBPS).

Progressive delivery: ``stream=`` requests a rollout view on every
discovery/notify fetch (the server resolves it against the token's
tenant policy — serving/rollout.py), and a ``stream="stable"`` reader
additionally refuses a canary-tagged descriptor CLIENT-side before the
verification pipeline even starts
(``tpuft_rollout_wrong_stream_rejects_total{seam="reader"}``) — a
misrouted or compromised tier cannot push a canary onto a stable
reader.
"""

from __future__ import annotations

import io
import logging
import threading
import time
import urllib.error
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax

from torchft_tpu import metrics
from torchft_tpu._safe_pickle import safe_loads
from torchft_tpu.checkpointing import _serialization
from torchft_tpu.serving._wire import (
    LATEST_PREV_ROUTE,
    LATEST_ROUTE,
    VERSION_ROUTE_PREFIX,
    PollPacer,
    chunk_crc,
    fetch_bytes,
    fetch_json,
    fetch_notify,
    newer_than_held,
    notify_enabled,
    same_stream,
    validate_latest,
)
from torchft_tpu.serving import rollout
from torchft_tpu.serving.relay import serving_poll_sec

__all__ = ["WeightSubscriber", "ServingVersion"]

logger = logging.getLogger(__name__)

# Deterministic default jitter seeds: readers created in the same order
# get the same seeds run to run (reproducible drills), while distinct
# readers spread across the jitter window.
_seed_lock = threading.Lock()
_seed_counter = 0


def _next_seed() -> int:
    global _seed_counter
    with _seed_lock:
        _seed_counter += 1
        return _seed_counter


@dataclass(frozen=True)
class ServingVersion:
    """One adopted version: the unflattened params plus its identity."""

    step: int
    quorum_id: Optional[int]
    digest: str
    params: Any
    ts: float
    pub_seq: Optional[int] = None
    pub_id: Optional[str] = None


class WeightSubscriber:
    """Polls serving endpoints and holds the newest verified version."""

    def __init__(
        self,
        endpoints: List[str],
        timeout: float = 10.0,
        token: Optional[str] = None,
        notify: Optional[bool] = None,
        poll_interval: Optional[float] = None,
        jitter_seed: Optional[int] = None,
        pin: Optional[Union[int, str]] = None,
        stream: Optional[str] = None,
    ) -> None:
        if not endpoints:
            raise ValueError("WeightSubscriber needs at least one endpoint")
        if pin is not None and not (
            isinstance(pin, int) or pin == "latest-1"
        ):
            raise ValueError(
                f"pin must be a step (int) or 'latest-1', got {pin!r}"
            )
        if stream is not None and stream not in (
            rollout.STREAM_STABLE,
            rollout.STREAM_CANARY,
            rollout.VIEW_ALL,
        ):
            raise ValueError(
                f"stream must be stable|canary|all, got {stream!r}"
            )
        self._endpoints = list(endpoints)
        self._timeout = timeout
        self._token = token
        self._pin = pin
        # Requested rollout view, sent on every discovery/notify fetch
        # (None = pre-rollout behavior: no query, no client-side fence).
        self._stream = stream
        # Pinned-step readers have a FIXED target: push notifications
        # announce newer versions, which is exactly what a pin ignores.
        self._notify = (
            (notify if notify is not None else notify_enabled())
            and not isinstance(pin, int)
        )
        self._pacer = PollPacer(
            poll_interval if poll_interval is not None else serving_poll_sec(),
            seed=jitter_seed if jitter_seed is not None else _next_seed(),
        )
        self._version: Optional[ServingVersion] = None
        # chunk index -> (crc, size, decoded chunk dict): the delta cache.
        self._chunk_cache: Dict[int, Tuple[int, int, Any]] = {}
        # tree_token -> treedef: the /meta-skip cache (sparse bumps reuse
        # the verified structure instead of paying the meta RTT).
        self._treedef_cache: Optional[Tuple[str, Any]] = None
        # A pinned step answered 410: the version was deliberately
        # retracted — the caller re-pins (e.g. to latest-1) instead of
        # polling a tombstone forever.
        self.pin_retracted = False
        # Round outcome flags for watch(): did the last wait_for_update
        # park a full quiet hold (no pacing needed), and did the last
        # poll actually FAIL (backoff) vs merely find nothing new
        # (plain jittered cadence)?
        self._held_full_round = False
        self._last_poll_failed = False

    def current(self) -> Optional[ServingVersion]:
        return self._version

    def poll(self) -> Optional[ServingVersion]:
        """One poll round; returns the newly adopted version, or None when
        there is nothing new (or this round failed — the held version is
        untouched either way)."""
        self._last_poll_failed = False
        try:
            return self._poll()
        except Exception as e:  # noqa: BLE001 — a failed poll is staleness
            self._last_poll_failed = True
            metrics.inc("tpuft_serving_reader_poll_failures_total")
            logger.warning("subscriber poll failed (%s); keeping held version", e)
            return None

    def wait_for_update(self, hold: Optional[float] = None):
        """One PUSH round: parks a long-poll ``/serving/notify`` at an
        endpoint until it announces a version newer than the held one (or
        the bounded ``hold`` expires), then runs the normal verify-then-
        swap poll. Returns the newly adopted version, or None (hold
        expired with nothing new / every endpoint failed / verification
        failed — the held version is untouched either way). With notify
        off this IS :meth:`poll`."""
        self._held_full_round = False
        if not self._notify:
            return self.poll()
        held = self._version
        after = held.step if held is not None else -1
        after_seq = held.pub_seq if held is not None else None
        after_pub = held.pub_id if held is not None else None
        for _ in range(len(self._endpoints)):
            endpoint = self._endpoints[0]
            try:
                descriptor = fetch_notify(
                    endpoint, after, self._timeout, token=self._token,
                    hold=hold, after_seq=after_seq, after_pub=after_pub,
                    stream=self._stream,
                )
            except Exception:  # noqa: BLE001 — endpoint dead or notify-less
                self._endpoints.append(self._endpoints.pop(0))
                metrics.inc("tpuft_serving_reader_failovers_total")
                continue
            if descriptor is None:
                # A full hold passed quietly — nothing new anywhere; the
                # caller re-arms without a poll-interval sleep.
                self._held_full_round = True
                return None
            # A notify woke us: adopt through the IDENTICAL verification
            # pipeline a poll runs (the descriptor itself is untrusted —
            # passing it in only skips the redundant /serving/latest
            # re-fetch, not one check).
            self._last_poll_failed = False
            try:
                return self._poll(latest=descriptor)
            except Exception as e:  # noqa: BLE001 — staleness, never adoption
                self._last_poll_failed = True
                metrics.inc("tpuft_serving_reader_poll_failures_total")
                logger.warning(
                    "subscriber push adoption failed (%s); keeping held version", e
                )
                return None
        # Every endpoint refused the long-poll: fall back (backoff).
        self._last_poll_failed = True
        return None

    def watch(
        self,
        stop: threading.Event,
        on_version: Optional[Callable[[ServingVersion], None]] = None,
    ) -> None:
        """Reader loop until ``stop``: long-poll rounds when notify is
        on (re-arming each bounded hold), deterministic-jittered polling
        with exponential backoff on failures as the fallback — so a
        reader population degrades from push to a spread herd, never to
        a synchronized one."""
        while not stop.is_set():
            version = self.wait_for_update()
            if version is not None:
                self._pacer.reset()
                if on_version is not None:
                    on_version(version)
                continue
            if self._held_full_round:
                continue  # the hold already paced this round
            if stop.wait(self._pacer.next_delay(failed=self._last_poll_failed)):
                return

    def _discovery_route(self) -> str:
        if isinstance(self._pin, int):
            route = f"{VERSION_ROUTE_PREFIX}{self._pin}"
        elif self._pin == "latest-1":
            route = LATEST_PREV_ROUTE
        else:
            route = LATEST_ROUTE
        if self._stream is not None:
            route += f"?stream={self._stream}"
        return route

    def _fetch_latest(self) -> Optional[Dict[str, Any]]:
        route = self._discovery_route()
        for _ in range(len(self._endpoints)):
            endpoint = self._endpoints[0]
            try:
                return fetch_json(
                    f"{endpoint}{route}", self._timeout, token=self._token
                )
            except urllib.error.HTTPError as e:
                if e.code == 410 and isinstance(self._pin, int):
                    # The pinned version was deliberately retracted: this
                    # is an ANSWER, not an endpoint failure — surface it
                    # instead of rotating through the fleet forever.
                    self.pin_retracted = True
                    metrics.inc("tpuft_serving_wrong_version_rejects_total")
                    return None
                self._endpoints.append(self._endpoints.pop(0))
                metrics.inc("tpuft_serving_reader_failovers_total")
            except Exception:  # noqa: BLE001 — fail over to the next endpoint
                # Rotate so a dead endpoint stops being everyone's first
                # try; it heals back in naturally once others fail.
                self._endpoints.append(self._endpoints.pop(0))
                metrics.inc("tpuft_serving_reader_failovers_total")
        return None

    def _poll(
        self, latest: Optional[Dict[str, Any]] = None
    ) -> Optional[ServingVersion]:
        if latest is None:
            latest = self._fetch_latest()
        if latest is None:
            self._last_poll_failed = True
            metrics.inc("tpuft_serving_reader_poll_failures_total")
            return None
        reason = validate_latest(latest)
        if reason is not None:
            metrics.inc("tpuft_serving_integrity_rejects_total")
            logger.warning("serving descriptor rejected: %s", reason)
            return None
        if (
            self._stream == rollout.STREAM_STABLE
            and latest.get("stream") == rollout.STREAM_CANARY
        ):
            # Reader-side wrong-stream fence: a stable reader refuses a
            # canary-tagged descriptor BEFORE the verification pipeline
            # starts — a misrouted or compromised tier cannot push a
            # canary onto a stable reader (server-side gating is the
            # routing; this is the belt-and-braces refusal).
            metrics.inc(
                "tpuft_rollout_wrong_stream_rejects_total", seam="reader"
            )
            logger.warning(
                "refusing canary version %s on a stable-stream reader",
                latest.get("step"),
            )
            return None
        held = self._version
        step = int(latest["step"])
        if isinstance(self._pin, int) and step != self._pin:
            # Pinned readers adopt EXACTLY their pin — any other version
            # offered under the pinned route is refused outright.
            metrics.inc("tpuft_serving_wrong_version_rejects_total")
            return None
        retraction = False
        if held is not None:
            if step == held.step and latest["digest"] == held.digest:
                return None  # identical version (possibly re-announced)
            stream = same_stream(latest, held.pub_seq, held.pub_id)
            if stream:
                # Same publication stream: seq ordering governs, and a
                # seq-newer descriptor at a LOWER step is a sanctioned
                # rollback (retraction re-announced V-1) — its era is
                # V-1's own, exempt from the regression fence below.
                if not newer_than_held(latest, held.step, held.pub_seq, held.pub_id):
                    return None
                retraction = step < held.step
            if not retraction:
                # Era fence (all forward motion, same stream or not): a
                # stale-era survivor announcing a higher step must never
                # roll readers back across quorum eras.
                if (
                    latest.get("quorum_id") is not None
                    and held.quorum_id is not None
                    and latest["quorum_id"] < held.quorum_id
                ):
                    metrics.inc("tpuft_serving_stale_era_rejects_total")
                    return None
                if not stream and step <= held.step:
                    return None
        base: str = latest["base"]
        algo: str = latest["crc_algo"]
        crcs: List[int] = [int(c) for c in latest["chunk_crcs"]]
        sizes: List[int] = [int(s) for s in latest["chunk_sizes"]]
        token = latest.get("tree_token")
        treedef = None
        if (
            token
            and self._treedef_cache is not None
            and self._treedef_cache[0] == token
        ):
            # Sparse bump, unchanged structure: skip the /meta RTT. The
            # adopted bytes still verify chunk-by-chunk against the
            # descriptor's digest-bound CRCs, so the torn-read fence
            # holds — it just no longer costs a round trip.
            treedef = self._treedef_cache[1]
            metrics.inc("tpuft_serving_meta_fetches_skipped_total")
        else:
            meta = safe_loads(
                fetch_bytes(
                    f"{base}/checkpoint/{step}/meta", self._timeout, token=self._token
                )
            )
            if (
                not isinstance(meta, dict)
                or meta.get("step") != step
                or meta.get("digest") != latest["digest"]
            ):
                # The serving side moved on between our descriptor and meta
                # fetches — abort THIS poll; the next one sees a consistent
                # pair. This is the fence that makes torn reads structurally
                # impossible.
                return None
            treedef = meta["treedef"]
            if token:
                self._treedef_cache = (token, treedef)
        new_cache: Dict[int, Tuple[int, int, Any]] = {}
        fetched_bytes = 0
        saved = 0
        for i in range(len(crcs)):
            cached = self._chunk_cache.get(i)
            if cached is not None and cached[0] == crcs[i] and cached[1] == sizes[i]:
                new_cache[i] = cached
                saved += sizes[i]
                continue
            data = fetch_bytes(
                f"{base}/checkpoint/{step}/{i}", self._timeout, token=self._token
            )
            if len(data) != sizes[i] or chunk_crc(data, algo) != crcs[i]:
                metrics.inc("tpuft_serving_integrity_rejects_total")
                raise ValueError(
                    f"chunk {i} of version {step} failed verification; "
                    "discarding this poll"
                )
            chunk = _serialization.load_state_dict(io.BytesIO(data))
            new_cache[i] = (crcs[i], sizes[i], chunk)
            fetched_bytes += len(data)
        merged: Dict[int, Any] = {}
        for _crc, _size, chunk in new_cache.values():
            merged.update(chunk)
        leaves = [merged[i] for i in range(treedef.num_leaves)]
        params = jax.tree_util.tree_unflatten(treedef, leaves)
        if latest.get("chunk_codecs"):
            # Quantized serving wire: decode AFTER every chunk verified
            # its digest-bound CRC. A lying/corrupt codec tag raises —
            # counted as an integrity reject, the poll fails, and the
            # held version stays; a bad tag can never become an adopted
            # version.
            from torchft_tpu import wire_codec

            try:
                params = wire_codec.decode_state(params, wire="serving")
            except wire_codec.WireCodecError as e:
                metrics.inc("tpuft_serving_integrity_rejects_total")
                raise ValueError(
                    f"version {step} failed codec validation: {e}"
                ) from e
        version = ServingVersion(
            step=step,
            quorum_id=latest.get("quorum_id"),
            digest=latest["digest"],
            params=params,
            ts=time.time(),
            pub_seq=latest.get("pub_seq"),
            pub_id=latest.get("pub_id"),
        )
        # The swap is the adoption point: everything above verified.
        self._version = version
        self._chunk_cache = new_cache
        metrics.inc("tpuft_serving_reader_versions_total")
        metrics.inc("tpuft_serving_reader_bytes_total", fetched_bytes)
        if retraction:
            metrics.inc("tpuft_serving_retraction_adoptions_total")
        if (
            saved
            and held is not None
            and held.pub_seq is not None
            and version.pub_seq is not None
            and version.pub_id == held.pub_id
            and version.pub_seq - held.pub_seq > 1
        ):
            # Delta CHAIN: this adoption crossed several published
            # versions (the reader lagged / was pinned / slept) yet still
            # moved only the chunks that changed since its held version —
            # strictly fewer bytes than a full refetch.
            metrics.inc(
                "tpuft_history_delta_chain_hops_total",
                version.pub_seq - held.pub_seq,
            )
        origin_ts = latest.get("origin_ts")
        if origin_ts is not None:
            # Publish-to-reader propagation (origin_ts is preserved
            # across relay tiers; cross-host this is NTP-quality).
            metrics.observe(
                "tpuft_serving_propagation_seconds",
                max(time.time() - float(origin_ts), 0.0),
            )
        if saved:
            metrics.inc("tpuft_serving_delta_bytes_saved_total", saved)
        return version
