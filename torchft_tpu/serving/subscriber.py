"""Reader client for the committed-weights serving plane.

A :class:`WeightSubscriber` polls one or more serving endpoints (relays
or publishers — they speak the same protocol) and atomically swaps to
the newest *fully verified* version:

- the ``/serving/latest`` descriptor must bind its digest to its
  per-chunk CRCs (checked before any transfer);
- the pickled ``/meta`` must carry the SAME digest (the torn-read fence:
  a version bump between the descriptor fetch and the meta fetch changes
  the digest, aborting this poll instead of mixing versions);
- every chunk verifies against its CRC and size before decode;
- only then does :meth:`current` flip to the new
  :class:`ServingVersion` — a reader can never observe a torn, partially
  adopted, or corrupt version, and a failed poll leaves the held version
  untouched.

Era discipline: a descriptor whose quorum era regresses below the held
version's is a stale-era read and is rejected
(``tpuft_serving_stale_era_rejects_total``); steps are monotone.

Delta-aware: decoded chunks are cached per index with their ``(crc,
size)``; a version bump re-decodes (and re-fetches) only chunks that
actually changed — the reader-side twin of the relay's delta pull.
"""

from __future__ import annotations

import io
import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax

from torchft_tpu import metrics
from torchft_tpu._safe_pickle import safe_loads
from torchft_tpu.checkpointing import _serialization
from torchft_tpu.serving._wire import (
    LATEST_ROUTE,
    chunk_crc,
    fetch_bytes,
    fetch_json,
    validate_latest,
)

__all__ = ["WeightSubscriber", "ServingVersion"]

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ServingVersion:
    """One adopted version: the unflattened params plus its identity."""

    step: int
    quorum_id: Optional[int]
    digest: str
    params: Any
    ts: float


class WeightSubscriber:
    """Polls serving endpoints and holds the newest verified version."""

    def __init__(self, endpoints: List[str], timeout: float = 10.0) -> None:
        if not endpoints:
            raise ValueError("WeightSubscriber needs at least one endpoint")
        self._endpoints = list(endpoints)
        self._timeout = timeout
        self._version: Optional[ServingVersion] = None
        # chunk index -> (crc, size, decoded chunk dict): the delta cache.
        self._chunk_cache: Dict[int, Tuple[int, int, Any]] = {}

    def current(self) -> Optional[ServingVersion]:
        return self._version

    def poll(self) -> Optional[ServingVersion]:
        """One poll round; returns the newly adopted version, or None when
        there is nothing new (or this round failed — the held version is
        untouched either way)."""
        try:
            return self._poll()
        except Exception as e:  # noqa: BLE001 — a failed poll is staleness
            metrics.inc("tpuft_serving_reader_poll_failures_total")
            logger.warning("subscriber poll failed (%s); keeping held version", e)
            return None

    def _fetch_latest(self) -> Optional[Dict[str, Any]]:
        for _ in range(len(self._endpoints)):
            endpoint = self._endpoints[0]
            try:
                return fetch_json(f"{endpoint}{LATEST_ROUTE}", self._timeout)
            except Exception:  # noqa: BLE001 — fail over to the next endpoint
                # Rotate so a dead endpoint stops being everyone's first
                # try; it heals back in naturally once others fail.
                self._endpoints.append(self._endpoints.pop(0))
                metrics.inc("tpuft_serving_reader_failovers_total")
        return None

    def _poll(self) -> Optional[ServingVersion]:
        latest = self._fetch_latest()
        if latest is None:
            metrics.inc("tpuft_serving_reader_poll_failures_total")
            return None
        reason = validate_latest(latest)
        if reason is not None:
            metrics.inc("tpuft_serving_integrity_rejects_total")
            logger.warning("serving descriptor rejected: %s", reason)
            return None
        held = self._version
        step = int(latest["step"])
        if held is not None:
            if step <= held.step:
                return None
            if (
                latest.get("quorum_id") is not None
                and held.quorum_id is not None
                and latest["quorum_id"] < held.quorum_id
            ):
                metrics.inc("tpuft_serving_stale_era_rejects_total")
                return None
        base: str = latest["base"]
        algo: str = latest["crc_algo"]
        crcs: List[int] = [int(c) for c in latest["chunk_crcs"]]
        sizes: List[int] = [int(s) for s in latest["chunk_sizes"]]
        meta = safe_loads(
            fetch_bytes(f"{base}/checkpoint/{step}/meta", self._timeout)
        )
        if (
            not isinstance(meta, dict)
            or meta.get("step") != step
            or meta.get("digest") != latest["digest"]
        ):
            # The serving side moved on between our descriptor and meta
            # fetches — abort THIS poll; the next one sees a consistent
            # pair. This is the fence that makes torn reads structurally
            # impossible.
            return None
        treedef = meta["treedef"]
        new_cache: Dict[int, Tuple[int, int, Any]] = {}
        fetched_bytes = 0
        saved = 0
        for i in range(len(crcs)):
            cached = self._chunk_cache.get(i)
            if cached is not None and cached[0] == crcs[i] and cached[1] == sizes[i]:
                new_cache[i] = cached
                saved += sizes[i]
                continue
            data = fetch_bytes(f"{base}/checkpoint/{step}/{i}", self._timeout)
            if len(data) != sizes[i] or chunk_crc(data, algo) != crcs[i]:
                metrics.inc("tpuft_serving_integrity_rejects_total")
                raise ValueError(
                    f"chunk {i} of version {step} failed verification; "
                    "discarding this poll"
                )
            chunk = _serialization.load_state_dict(io.BytesIO(data))
            new_cache[i] = (crcs[i], sizes[i], chunk)
            fetched_bytes += len(data)
        merged: Dict[int, Any] = {}
        for _crc, _size, chunk in new_cache.values():
            merged.update(chunk)
        leaves = [merged[i] for i in range(treedef.num_leaves)]
        params = jax.tree_util.tree_unflatten(treedef, leaves)
        version = ServingVersion(
            step=step,
            quorum_id=latest.get("quorum_id"),
            digest=latest["digest"],
            params=params,
            ts=time.time(),
        )
        # The swap is the adoption point: everything above verified.
        self._version = version
        self._chunk_cache = new_cache
        metrics.inc("tpuft_serving_reader_versions_total")
        metrics.inc("tpuft_serving_reader_bytes_total", fetched_bytes)
        if saved:
            metrics.inc("tpuft_serving_delta_bytes_saved_total", saved)
        return version
