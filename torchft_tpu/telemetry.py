"""Structured event telemetry.

Role-equivalent of the reference's ``torchft/otel.py``: named event loggers
``tpuft_quorums`` / ``tpuft_commits`` / ``tpuft_errors`` receive one record
per quorum change, commit decision, and error, each carrying
job_id/replica_id/rank/quorum_id/step fields in ``record.__dict__``.

Export is opt-in via ``TPUFT_TELEMETRY``:
  - ``console``: JSON lines to stderr
  - ``file:<path>``: JSON lines appended to <path>
  - unset: records flow to whatever handlers the application configures
    (opentelemetry's LoggingHandler attaches cleanly to these loggers when
    available — it is not bundled in this environment).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Dict

__all__ = ["quorums_logger", "commits_logger", "errors_logger", "configure_telemetry"]

quorums_logger = logging.getLogger("tpuft_quorums")
commits_logger = logging.getLogger("tpuft_commits")
errors_logger = logging.getLogger("tpuft_errors")

_EVENT_FIELDS = (
    "job_id",
    "replica_id",
    "rank",
    "quorum_id",
    "step",
    "commit_result",
    "error",
)


class _JsonLinesHandler(logging.Handler):
    def __init__(self, stream: Any) -> None:
        super().__init__()
        self._stream = stream

    def emit(self, record: logging.LogRecord) -> None:
        event: Dict[str, Any] = {
            "ts": time.time(),
            "event": record.name,
            "message": record.getMessage(),
        }
        for field in _EVENT_FIELDS:
            if hasattr(record, field):
                event[field] = getattr(record, field)
        try:
            self._stream.write(json.dumps(event) + "\n")
            self._stream.flush()
        except Exception:  # noqa: BLE001
            self.handleError(record)


def configure_telemetry(mode: str | None = None) -> None:
    """Attaches exporters per ``mode`` (defaults to $TPUFT_TELEMETRY)."""
    mode = mode if mode is not None else os.environ.get("TPUFT_TELEMETRY", "")
    if not mode:
        return
    if mode == "console":
        handler: logging.Handler = _JsonLinesHandler(sys.stderr)
    elif mode.startswith("file:"):
        handler = _JsonLinesHandler(open(mode[len("file:") :], "a"))
    else:
        raise ValueError(f"unknown TPUFT_TELEMETRY mode: {mode}")
    for event_logger in (quorums_logger, commits_logger, errors_logger):
        event_logger.addHandler(handler)
        event_logger.setLevel(logging.INFO)


configure_telemetry()
