"""Structured event telemetry.

Role-equivalent of the reference's ``torchft/otel.py``: named event loggers
``tpuft_quorums`` / ``tpuft_commits`` / ``tpuft_errors`` receive one record
per quorum change, commit decision, and error, each carrying
job_id/replica_id/rank/quorum_id/step fields in ``record.__dict__``.

Export is opt-in via ``TPUFT_TELEMETRY``:
  - ``console``: JSON lines to stderr
  - ``file:<path>``: JSON lines appended to <path>
  - ``otlp``: attach opentelemetry's LoggingHandler (requires the
    ``opentelemetry-sdk`` packages; endpoint/resource attributes come from
    the standard ``OTEL_*`` env, mirroring the reference's
    ``TORCHFT_USE_OTEL`` path, otel.py:42-79)
  - unset: records flow to whatever handlers the application configures.

Telemetry narrates (one record per event, with ids); the fleet metrics
plane (``torchft_tpu.metrics``) counts — per-phase histograms and
commit/rollback/heal counters served on ``/metrics`` and pushed to the
group store for ``scripts/fleet_status.py``. Correlate the two through
quorum_id/step; docs/observability.md is the combined debugging guide.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Dict

__all__ = [
    "quorums_logger",
    "commits_logger",
    "errors_logger",
    "slo_logger",
    "configure_telemetry",
]

quorums_logger = logging.getLogger("tpuft_quorums")
commits_logger = logging.getLogger("tpuft_commits")
errors_logger = logging.getLogger("tpuft_errors")
# SLO-breach records (goodput burn-rate alerts, torchft_tpu/goodput.py):
# one record per latched breach, carrying the slo/burn/goodput fields below.
slo_logger = logging.getLogger("tpuft_slo")

_EVENT_FIELDS = (
    "job_id",
    "replica_id",
    "rank",
    "quorum_id",
    "step",
    "commit_result",
    "error",
    "slo",
    "slo_target",
    "burn_rate",
    "goodput",
    "windows",
)


class _JsonLinesHandler(logging.Handler):
    def __init__(self, stream: Any) -> None:
        super().__init__()
        self._stream = stream

    def emit(self, record: logging.LogRecord) -> None:
        event: Dict[str, Any] = {
            "ts": time.time(),
            "event": record.name,
            "message": record.getMessage(),
        }
        for field in _EVENT_FIELDS:
            if hasattr(record, field):
                event[field] = getattr(record, field)
        try:
            self._stream.write(json.dumps(event) + "\n")
            self._stream.flush()
        except Exception:  # noqa: BLE001
            self.handleError(record)


def configure_telemetry(mode: str | None = None) -> None:
    """Attaches exporters per ``mode`` (defaults to $TPUFT_TELEMETRY)."""
    mode = mode if mode is not None else os.environ.get("TPUFT_TELEMETRY", "")
    if not mode:
        return
    if mode == "console":
        handler: logging.Handler = _JsonLinesHandler(sys.stderr)
    elif mode.startswith("file:"):
        handler = _JsonLinesHandler(open(mode[len("file:") :], "a"))
    elif mode == "otlp":
        handler = _make_otlp_handler()
    else:
        raise ValueError(f"unknown TPUFT_TELEMETRY mode: {mode}")
    for event_logger in (quorums_logger, commits_logger, errors_logger, slo_logger):
        event_logger.addHandler(handler)
        event_logger.setLevel(logging.INFO)


def _make_otlp_handler() -> logging.Handler:
    """Builds an opentelemetry LoggingHandler backed by a batch OTLP log
    exporter. Raises a clear error when the (optional) SDK is absent."""
    try:
        from opentelemetry.exporter.otlp.proto.grpc._log_exporter import (
            OTLPLogExporter,
        )
        from opentelemetry.sdk._logs import LoggerProvider, LoggingHandler
        from opentelemetry.sdk._logs.export import BatchLogRecordProcessor
    except ImportError as e:  # pragma: no cover - env-dependent
        raise RuntimeError(
            "TPUFT_TELEMETRY=otlp requires the opentelemetry-sdk and "
            "opentelemetry-exporter-otlp packages (endpoint via OTEL_EXPORTER_"
            "OTLP_ENDPOINT); use 'console' or 'file:<path>' otherwise"
        ) from e
    provider = LoggerProvider()
    provider.add_log_record_processor(BatchLogRecordProcessor(OTLPLogExporter()))
    # The provider is passed explicitly; no global set_logger_provider side
    # effect (it would race an application-configured OTel provider).
    return LoggingHandler(level=logging.INFO, logger_provider=provider)


configure_telemetry()
