"""Fleet trace plane: per-process step-event journals that merge into one
causally ordered cross-replica timeline.

The other observability surfaces are *per-process*: metrics count
(metrics.py), telemetry narrates single records (telemetry.py), the flight
recorder rings raw PG events (utils/flight_recorder.py), chrome spans show
one process's overlap (utils/profiling.py). None of them answers "who
stalled step N's commit barrier?" across a fleet with unsynchronized wall
clocks. This module adds the missing layer:

- :class:`TraceJournal` — a bounded ring of structured span/instant events,
  one journal per process (threads-as-replicas tests get one per replica
  thread via :func:`use_journal`). Every FT phase records here: quorum
  begin/end, ``pg.configure``, update dispatch, device sync, vote
  send/resolve, commit/rollback, heal chunk progress, serve-child
  lifecycle, ZeRO re-balance. Each event carries the full causal tuple
  ``(job_id, replica_id, group_rank, step, quorum_id, seq, t_mono,
  t_wall)`` — ``(step, quorum_id, seq)`` is the hybrid logical clock that
  keeps merged timelines causally ordered even when wall clocks drift.
- clock alignment — :class:`StoreClockSampler` samples a coarse wall-clock
  offset against a store-mediated beacon key (``trace/clockref``), riding
  the metrics-push cadence; precision is bounded by that cadence, so it
  catches *gross* skew (unsynced hosts seconds/minutes apart). Fine
  alignment happens at merge time from barrier-simultaneity anchors
  (``scripts/fleet_trace.py``): every participant's commit-barrier release
  is quorum-wide simultaneous within RPC fanout skew.
- fleet collection — each Manager pushes journal segments to its group
  store at ``trace/<replica_id>/<group_rank>`` (same cadence as the
  metrics push) and every metrics HTTP surface serves the full ring as
  ``GET /trace.json``.
- incident auto-capture — :func:`open_incident` stamps a *deterministic*
  incident id (pure function of kind/step/quorum_id, so every process
  observing the same quorum-wide event derives the same id with zero
  coordination) and dumps journal + flight-recorder ring under
  ``$TPUFT_FLIGHT_RECORDER``. Triggers: rollback, quorum timeout,
  ``HealExhaustedError``.

Recording is always on (a dict build + deque append per event — the
per-event cost is pinned by a unit test); ``TPUFT_TRACE=0`` disables it.
The ring holds ``TPUFT_TRACE_SIZE`` events (default 8192).
``TPUFT_TRACE_CLOCK=0`` disables the store beacon sampling.

Journal recording NEVER takes the state-dict lock — recording sites are
plain deque appends, safe inside any phase including the commit barrier
(the R3 lock-discipline fixtures pin the pattern).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Deque, Dict, Generator, List, Optional

import collections

from torchft_tpu import metrics

__all__ = [
    "TraceJournal",
    "StoreClockSampler",
    "ENV_TRACE",
    "ENV_SIZE",
    "ENV_CLOCK",
    "CLOCK_REF_KEY",
    "STORE_PREFIX",
    "default",
    "current",
    "use_journal",
    "configure",
    "set_step",
    "record",
    "span",
    "incident_id",
    "open_incident",
    "active_incident",
    "clear_incident",
    "trace_json_payload",
]

ENV_TRACE = "TPUFT_TRACE"
ENV_SIZE = "TPUFT_TRACE_SIZE"
ENV_CLOCK = "TPUFT_TRACE_CLOCK"

# Well-known store keys: the beacon every process samples against, and the
# per-process segment keys fleet_status/fleet_trace read.
CLOCK_REF_KEY = "trace/clockref"
STORE_PREFIX = "trace"

# Span names the per-step phase rollup aggregates (the STRAGGLER/LAG feed
# for scripts/fleet_status.py and --explain-step's phase deltas).
PHASE_SPANS = (
    "quorum",
    "pg_configure",
    "wire_bucket",
    "device_sync",
    "update_dispatch",
    "commit_barrier",
    "heal_send",
    "heal_recv",
    "zero_rebalance",
    "pipeline_drain",
)


def _enabled_from_env() -> bool:
    return os.environ.get(ENV_TRACE, "1") != "0"


def _ring_size() -> int:
    try:
        return max(64, int(os.environ.get(ENV_SIZE, "8192")))
    except ValueError:
        return 8192  # malformed env must not break package import


def _jsonable(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool, type(None))):
        return value
    try:
        return repr(value)
    except Exception:  # pathological __repr__
        return f"<unreprable {type(value).__name__}>"


class TraceJournal:
    """Bounded per-process journal of structured FT-phase events.

    Thread-safe the cheap way: deque appends are atomic, ``seq`` comes from
    ``itertools.count`` (atomic in CPython), and identity/step fields are
    plain attribute reads — races on them only mislabel an event's step by
    one, which the merge's hybrid logical clock tolerates. ``wall``/``mono``
    are injectable so tests can skew clocks per journal and prove the
    alignment machinery recovers them.
    """

    def __init__(
        self,
        maxlen: Optional[int] = None,
        wall: Callable[[], float] = time.time,
        mono: Callable[[], float] = time.monotonic,
        enabled: Optional[bool] = None,
    ) -> None:
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=maxlen or _ring_size()
        )
        self._seq = itertools.count()
        self._last_seq = -1  # last seq handed out (for drop accounting)
        self._last_drained = -1
        self._wall = wall
        self._mono = mono
        self._enabled = _enabled_from_env() if enabled is None else enabled
        # Identity (stamped onto events at export, not per append).
        self.job_id = "unknown"
        self.replica_id = "proc"
        self.group_rank = 0
        # Hybrid-logical-clock context, maintained by the Manager.
        self.step = 0
        self.quorum_id = -1
        # Last coarse store-sampled clock offset (seconds, my_wall - ref_wall).
        self.clock_offset_s: Optional[float] = None
        self.active_incident: Optional[str] = None

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        job_id: Optional[str] = None,
        replica_id: Optional[str] = None,
        group_rank: Optional[int] = None,
    ) -> None:
        if job_id is not None:
            self.job_id = str(job_id)
        if replica_id is not None:
            self.replica_id = str(replica_id)
        if group_rank is not None:
            self.group_rank = int(group_rank)

    def set_step(
        self, step: Optional[int] = None, quorum_id: Optional[int] = None
    ) -> None:
        if step is not None:
            self.step = int(step)
        if quorum_id is not None:
            self.quorum_id = int(quorum_id)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- recording ----------------------------------------------------------

    def record(
        self,
        name: str,
        ph: str = "i",
        cat: str = "ft",
        dur: Optional[float] = None,
        step: Optional[int] = None,
        quorum_id: Optional[int] = None,
        t_wall: Optional[float] = None,
        t_mono: Optional[float] = None,
        **args: Any,
    ) -> None:
        """Appends one event. ``ph`` is chrome-trace-flavored: ``"X"`` a
        complete span (``dur`` seconds; stamps default to *start* = now -
        dur), ``"i"`` an instant. Never raises — recording sites include
        failure paths that must stay clean."""
        if not self._enabled:
            return
        try:
            seq = next(self._seq)
            self._last_seq = seq
            now_wall = self._wall()
            now_mono = self._mono()
            back = dur or 0.0
            event: Dict[str, Any] = {
                "seq": seq,
                "name": name,
                "ph": ph,
                "cat": cat,
                "t_wall": now_wall - back if t_wall is None else t_wall,
                "t_mono": now_mono - back if t_mono is None else t_mono,
                "thread": threading.current_thread().name,
                "step": self.step if step is None else step,
                "quorum_id": self.quorum_id if quorum_id is None else quorum_id,
            }
            if dur is not None:
                event["dur"] = dur
            if args:
                event["args"] = {k: _jsonable(v) for k, v in args.items()}
            self._ring.append(event)
        except Exception:  # noqa: BLE001 — observability must not wound
            pass

    @contextmanager
    def span(
        self,
        name: str,
        cat: str = "ft",
        step: Optional[int] = None,
        quorum_id: Optional[int] = None,
        **args: Any,
    ) -> Generator[None, None, None]:
        """Times the with-body into one ``"X"`` event (recorded at exit
        with the *start* timestamps, so merged timelines sort by entry)."""
        if not self._enabled:
            yield
            return
        start_wall = self._wall()
        start_mono = self._mono()
        try:
            yield
        finally:
            self.record(
                name,
                ph="X",
                cat=cat,
                dur=self._mono() - start_mono,
                step=step,
                quorum_id=quorum_id,
                t_wall=start_wall,
                t_mono=start_mono,
                **args,
            )

    # -- export -------------------------------------------------------------

    def _identity(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "replica_id": self.replica_id,
            "group_rank": self.group_rank,
        }

    def _copy_ring(self) -> List[Dict[str, Any]]:
        # Deque appends are atomic but iteration can race a concurrent
        # append; retry then fall back to an index walk (flight-recorder
        # pattern — a slightly short sample is fine for observability).
        for _ in range(4):
            try:
                return list(self._ring)
            except RuntimeError:
                continue
        out: List[Dict[str, Any]] = []
        for i in range(len(self._ring)):
            try:
                out.append(self._ring[i])
            except IndexError:
                break
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        """Identity-stamped copies of every ring event (oldest first)."""
        ident = self._identity()
        return [{**ident, **event} for event in self._copy_ring()]

    def dropped(self) -> int:
        """Events overwritten by the ring bound so far."""
        maxlen = self._ring.maxlen or 0
        return max(0, (self._last_seq + 1) - maxlen) if maxlen else 0

    def drain_segment(self) -> List[Dict[str, Any]]:
        """Events recorded since the last drain (identity-stamped) — the
        incremental store-push payload. Counts exported events and any that
        fell off the ring un-exported into the ``tpuft_trace_*`` metrics."""
        ring = self._copy_ring()
        previous = self._last_drained
        segment = [e for e in ring if e["seq"] > previous]
        if segment:
            self._last_drained = segment[-1]["seq"]
        # Events that fell off the ring BEFORE any drain exported them
        # (ring-bound drops of already-drained events are benign — they
        # were pushed).
        oldest = ring[0]["seq"] if ring else self._last_seq + 1
        missed = max(0, oldest - (previous + 1))
        if missed > 0:
            metrics.inc("tpuft_trace_dropped_total", missed)
        if segment:
            metrics.inc("tpuft_trace_events_total", len(segment))
        ident = self._identity()
        return [{**ident, **event} for event in segment]

    def phase_rollup(self, max_steps: int = 4) -> List[Dict[str, Any]]:
        """Per-step phase durations from the ring (latest ``max_steps``
        steps): ``{"step", "quorum_id", "phases": {span: seconds},
        "committed": bool|None}``. Durations are *local monotonic* — clock-
        free, so fleet_status can compare them across replicas directly
        (the straggler entered the commit barrier last and therefore
        waited in it least)."""
        by_step: Dict[int, Dict[str, Any]] = {}
        for event in self._copy_ring():
            step = event.get("step")
            if step is None:
                continue
            name = event.get("name")
            slot = by_step.setdefault(
                step,
                {"step": step, "quorum_id": event.get("quorum_id"),
                 "phases": {}, "committed": None},
            )
            if event.get("ph") == "X" and name in PHASE_SPANS:
                slot["phases"][name] = round(
                    slot["phases"].get(name, 0.0) + float(event.get("dur", 0.0)), 6
                )
                slot["quorum_id"] = event.get("quorum_id", slot["quorum_id"])
            elif name == "commit":
                slot["committed"] = True
            elif name == "commit_failed":
                slot["committed"] = False
        steps = sorted(by_step)[-max_steps:]
        return [by_step[s] for s in steps]

    # -- dump ---------------------------------------------------------------

    def dump(self, path: Optional[str] = None, reason: str = "") -> Optional[str]:
        """Writes the ring as JSON lines (header first). With no ``path``,
        uses ``$TPUFT_FLIGHT_RECORDER/tpuft_trace_<replica>_<rank>_<pid>_
        <ns>[_<incident>].jsonl`` — or returns None when the env is unset.
        Atomic (tmp + replace): a chaos kill mid-dump must never leave a
        truncated JSONL at the final name."""
        if path is None:
            directory = os.environ.get("TPUFT_FLIGHT_RECORDER", "")
            if not directory:
                return None
            os.makedirs(directory, exist_ok=True)
            suffix = f"_{self.active_incident}" if self.active_incident else ""
            path = os.path.join(
                directory,
                f"tpuft_trace_{sanitize(self.replica_id)}_{self.group_rank}"
                f"_{os.getpid()}_{time.time_ns()}{suffix}.jsonl",
            )
        header = {
            "trace_header": True,
            **self._identity(),
            "reason": reason,
            "incident": self.active_incident,
            "wall": self._wall(),
            "mono": self._mono(),
            "clock_offset_s": self.clock_offset_s,
            "dropped": self.dropped(),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for event in self.snapshot():
                f.write(json.dumps(event) + "\n")
        os.replace(tmp, path)
        return path


def sanitize(name: str) -> str:
    """Filesystem-safe identity fragment for dump filenames."""
    return (
        "".join(c if (c.isalnum() or c in "-._") else "-" for c in str(name))
        or "proc"
    )


# ---------------------------------------------------------------------------
# process default + per-thread journals (threads-as-replicas drills)
# ---------------------------------------------------------------------------

_PROCESS = TraceJournal()
_TLS = threading.local()


def default() -> TraceJournal:
    """The process-wide journal (one real deployment process = one rank)."""
    return _PROCESS


def current() -> TraceJournal:
    """The journal for this thread: a :func:`use_journal` override when one
    is active (threads-as-replicas tests give each replica thread its own
    journal), else the process journal. A Manager captures ``current()`` at
    construction, so events it records from its quorum thread still land in
    its replica's journal."""
    return getattr(_TLS, "journal", None) or _PROCESS


@contextmanager
def use_journal(journal: TraceJournal) -> Generator[TraceJournal, None, None]:
    previous = getattr(_TLS, "journal", None)
    _TLS.journal = journal
    try:
        yield journal
    finally:
        _TLS.journal = previous


def configure(**kwargs: Any) -> None:
    current().configure(**kwargs)


def set_step(step: Optional[int] = None, quorum_id: Optional[int] = None) -> None:
    current().set_step(step, quorum_id)


def record(name: str, **kwargs: Any) -> None:
    current().record(name, **kwargs)


def span(name: str, **kwargs: Any):
    return current().span(name, **kwargs)


# ---------------------------------------------------------------------------
# incidents
# ---------------------------------------------------------------------------


def incident_id(kind: str, step: int, quorum_id: int) -> str:
    """Deterministic incident id: every process observing the same
    quorum-wide event (a rollback at step N under quorum Q, a heal
    exhaustion) derives the SAME id with no coordination, so offline dumps
    from N hosts correlate by filename alone."""
    return f"inc-{kind}-q{quorum_id}-s{step}"


def open_incident(
    kind: str,
    step: int,
    quorum_id: int,
    journal: Optional[TraceJournal] = None,
    reason: str = "",
) -> str:
    """Stamps an incident: records the event, marks the id active (flight-
    recorder dumps reuse it in their filenames until the next commit
    clears it), dumps this journal AND the flight-recorder ring under
    ``$TPUFT_FLIGHT_RECORDER`` (no-ops when unset). Never raises."""
    j = journal or current()
    iid = incident_id(kind, step, quorum_id)
    try:
        j.record(
            "incident", cat="incident", step=step, quorum_id=quorum_id,
            kind=kind, incident=iid, reason=reason,
        )
        j.active_incident = iid
        metrics.inc("tpuft_trace_incidents_total", kind=kind)
        j.dump(reason=f"{kind}: {reason}")
        from torchft_tpu.utils import flight_recorder

        # Bind the journal as this thread's current while the flight
        # recorder dumps: incidents often open on the quorum thread, and
        # the FR filename reads identity + incident from tracing.current().
        with use_journal(j):
            flight_recorder.dump_on_failure("tracing", f"incident {iid}: {reason}")
    except Exception:  # noqa: BLE001 — incident capture must never wound
        pass
    return iid


def active_incident(journal: Optional[TraceJournal] = None) -> Optional[str]:
    return (journal or current()).active_incident


def clear_incident(journal: Optional[TraceJournal] = None) -> None:
    (journal or current()).active_incident = None


# ---------------------------------------------------------------------------
# /trace.json payload (served by every metrics HTTP surface)
# ---------------------------------------------------------------------------


def trace_json_payload(journal: Optional[TraceJournal] = None) -> Dict[str, Any]:
    j = journal or default()
    return {
        "ts": time.time(),
        "job_id": j.job_id,
        "replica_id": j.replica_id,
        "group_rank": j.group_rank,
        "step": j.step,
        "quorum_id": j.quorum_id,
        "clock": {
            "wall": j._wall(),
            "mono": j._mono(),
            "offset_s": j.clock_offset_s,
        },
        "incident": j.active_incident,
        "dropped": j.dropped(),
        "events": j.snapshot(),
        "phases": j.phase_rollup(),
    }


# ---------------------------------------------------------------------------
# store-mediated coarse clock sampling
# ---------------------------------------------------------------------------


class StoreClockSampler:
    """Coarse wall-clock offset sampling through a shared KV store.

    Protocol (pure get/set — the store has no server clock or listing):
    rank-0 managers race to own the ``trace/clockref`` beacon; ownership
    converges to the smallest owner key (each claimer only overwrites when
    it sorts at-or-below the current owner), with stale takeover when the
    beacon's counter stops advancing (dead owner). Everyone else samples:
    a beacon whose counter ADVANCED since our previous read was written
    inside our (prev_read, now] window, so
    ``offset = now - window/2 - beacon.wall`` with error ± window/2 —
    bounded by the push cadence. That catches gross skew (hosts seconds or
    minutes apart); fine alignment comes from barrier anchors at merge
    time (scripts/fleet_trace.py). Best-effort everywhere: a dead store
    never wounds a step.
    """

    STALE_TAKEOVER_READS = 3

    def __init__(
        self,
        journal: TraceJournal,
        owner_key: str,
        claim: bool = False,
        key: str = CLOCK_REF_KEY,
    ) -> None:
        self._journal = journal
        self._owner_key = str(owner_key)
        self._claim = claim
        self._key = key
        self._n = 0
        self._last_seen_n: Optional[int] = None
        self._stale_reads = 0
        self._last_read_wall: Optional[float] = None
        self._enabled = os.environ.get(ENV_CLOCK, "1") != "0"
        self.last_offset_s: Optional[float] = None

    def tick(self, store: Any) -> None:
        """One sampling round (call at the metrics-push cadence)."""
        if not self._enabled:
            return
        try:
            self._tick(store)
        except Exception:  # noqa: BLE001 — observability must not wound
            pass

    def _tick(self, store: Any) -> None:
        now = self._journal._wall()
        raw = store.get(self._key, timeout=2.0, wait=False)
        beacon = json.loads(raw.decode()) if raw else None

        should_claim = False
        if self._claim:
            if beacon is None:
                should_claim = True
            else:
                owner = str(beacon.get("owner", ""))
                if owner == self._owner_key or self._owner_key < owner:
                    should_claim = True
                elif beacon.get("n") == self._last_seen_n:
                    self._stale_reads += 1
                    if self._stale_reads >= self.STALE_TAKEOVER_READS:
                        should_claim = True  # owner stopped heartbeating
                else:
                    self._stale_reads = 0

        if beacon is not None and str(beacon.get("owner")) != self._owner_key:
            n = beacon.get("n")
            if n != self._last_seen_n and self._last_read_wall is not None:
                # The write landed between our previous read and this one
                # (in OUR clock): midpoint estimate, error ± window/2.
                window = max(0.0, now - self._last_read_wall)
                offset = (now - window / 2.0) - float(beacon.get("wall", now))
                self.last_offset_s = offset
                self._journal.clock_offset_s = offset
                self._journal.record(
                    "clock_sample",
                    cat="clock",
                    ref_owner=str(beacon.get("owner")),
                    ref_n=n,
                    ref_wall=float(beacon.get("wall", now)),
                    window_s=round(window, 6),
                    offset_s=offset,
                )
                metrics.set_gauge("tpuft_trace_clock_offset_ms", offset * 1e3)
            self._last_seen_n = n
        elif beacon is not None:
            # We are the owner: our frame IS the beacon frame.
            self.last_offset_s = 0.0
            self._journal.clock_offset_s = 0.0
        self._last_read_wall = now

        if should_claim:
            self._n += 1
            store.set(
                self._key,
                json.dumps(
                    {"owner": self._owner_key, "n": self._n,
                     "wall": self._journal._wall()}
                ).encode(),
            )
