"""Shared utilities: profiling spans, timing helpers."""

from torchft_tpu.utils.profiling import trace_span, timed

__all__ = ["trace_span", "timed"]
