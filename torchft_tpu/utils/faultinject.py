"""File-armed fault injection for chaos drills.

The punisher's heal-path fault modes (``corrupt_stream``, ``stall_donor``)
cannot ride the native kill RPC — they must misbehave *inside* a healthy
process's serving path. Instead the punisher arms a fault by writing the
mode name into ``$TPUFT_FAULT_FILE``; the first instrumented site that
matches the fault's target claims it atomically (``os.replace`` of the
file — losers of the race see it gone), so each arm injects **exactly
one** fault. An optional ``mode:site`` form restricts the fault to one
instrumentation site. Sites form ``:``-separated families: an arm
targeted at ``heal_stream`` matches any site under it (e.g. a donor's
port-tagged ``heal_stream:58311``), while an arm targeted at the full
tagged site hits exactly that donor — how the stripe drills corrupt one
donor of a multi-donor heal without touching its peers.

Production cost when unarmed: one env lookup per check (no filesystem
touch unless the env var is set). This module is a chaos tool, not a
control plane: a fault that is never consumed is harmless, and consuming
is best-effort (any OSError reads as "nothing armed").
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["ENV_FAULT_FILE", "arm", "consume"]

ENV_FAULT_FILE = "TPUFT_FAULT_FILE"


def arm(mode: str, path: Optional[str] = None, site: str = "") -> str:
    """Arms ``mode`` (optionally scoped to ``site``) by atomically writing
    the fault file. Returns the path written. Raises ValueError when no
    path is given and ``$TPUFT_FAULT_FILE`` is unset."""
    path = path or os.environ.get(ENV_FAULT_FILE)
    if not path:
        raise ValueError(
            f"no fault file: pass path= or set ${ENV_FAULT_FILE}"
        )
    payload = f"{mode}:{site}" if site else mode
    tmp = f"{path}.arming.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)  # atomic vs concurrent consume()
    return path


def consume(site: str) -> Optional[str]:
    """Returns (and atomically claims) the armed fault mode matching
    ``site``, or None when nothing is armed for it. The armed target
    matches its whole site family: target ``a`` claims sites ``a`` and
    ``a:anything``; target ``a:b`` claims only ``a:b`` (and deeper)."""
    path = os.environ.get(ENV_FAULT_FILE)
    if not path:
        return None
    try:
        with open(path, "r") as f:
            content = f.read().strip()
    except OSError:
        return None
    if not content:
        return None
    mode, _, target = content.partition(":")
    if target and site != target and not site.startswith(target + ":"):
        return None
    try:
        # The rename IS the claim: exactly one concurrent consumer wins,
        # the rest see FileNotFoundError and report nothing armed.
        os.replace(path, f"{path}.consumed")
    except OSError:
        return None
    return mode
