"""In-memory flight recorder for post-mortem debugging of wedged or
aborted collectives.

Role-equivalent of the reference's NCCL flight-recorder hookup: on PG
abort it triggers an NCCL FR trace dump through a pipe
(/root/reference/torchft/process_group.py:93-107, gated by
``TORCHFT_TRIGGER_FR_ON_ABORT``). TPU collectives have no NCCL FR, so
the framework keeps its own bounded ring of recent events — every PG op
submit/complete/error, configure, abort, and manager error funnels in —
and dumps it as JSON lines when things go wrong.

Always on (a deque append per event is noise next to any wire op); the
DUMP is opt-in: set ``TPUFT_FLIGHT_RECORDER`` to a directory and every
abort / reported error writes a fresh
``tpuft_fr_<replica>_<rank>_<pid>_<ns>[_<incident>].jsonl`` there — the
replica identity comes from the trace plane (``torchft_tpu.tracing``),
because a pid alone cannot be correlated across hosts, and when an
incident is active (a rollback, quorum timeout, or heal exhaustion
stamped its deterministic id) the filename carries it so one fleet-wide
event's dumps from N hosts correlate by name alone. ``dump()`` can also
be called explicitly with a path (e.g. from a debugger or a supervisor's
crash handler).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional

__all__ = ["record", "dump", "dump_on_failure", "snapshot", "ENV_DIR"]

ENV_DIR = "TPUFT_FLIGHT_RECORDER"
ENV_SIZE = "TPUFT_FLIGHT_RECORDER_SIZE"

def _ring_size() -> int:
    try:
        return max(16, int(os.environ.get(ENV_SIZE, "2048")))
    except ValueError:
        return 2048  # malformed env must not break package import


_RING: Deque[Dict[str, Any]] = collections.deque(maxlen=_ring_size())
_SEQ = itertools.count()
_DUMP_LOCK = threading.Lock()


def _jsonable(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool, type(None))):
        return value
    try:
        return repr(value)
    except Exception:  # pathological __repr__ on an exception object
        return f"<unreprable {type(value).__name__}>"


def record(source: str, event: str, **detail: Any) -> None:
    """Appends one entry to the ring. Thread-safe (deque appends are
    atomic); ``detail`` values are coerced to JSON-safe scalars. Never
    raises — it is called from failure paths that must stay clean."""
    try:
        _RING.append(
            {
                "seq": next(_SEQ),
                "ts": time.time(),
                "thread": threading.current_thread().name,
                "source": source,
                "event": event,
                **{k: _jsonable(v) for k, v in detail.items()},
            }
        )
    except Exception:
        pass


def snapshot() -> List[Dict[str, Any]]:
    """A consistent copy of the current ring (oldest first). Deque appends
    are atomic but iterating while another thread appends can raise
    'deque mutated during iteration' — retry briefly, then fall back to
    an index-walk copy (possibly missing the newest entries, which is
    fine for a post-mortem ring)."""
    return _snapshot_meta()[0]


def _snapshot_meta() -> "tuple[List[Dict[str, Any]], bool]":
    """(entries, truncated): ``truncated`` is True when the index-walk
    fallback fired — on a wrapped ring under concurrent appends entry i
    can shift mid-walk, so the sample may be non-contiguous; dumps record
    it so readers know (round-3 verdict)."""
    for _ in range(4):
        try:
            return list(_RING), False
        except RuntimeError:
            continue
    out: List[Dict[str, Any]] = []
    for i in range(len(_RING)):
        try:
            out.append(_RING[i])
        except IndexError:
            break
    return out, True


def _metrics_trailer() -> Optional[Dict[str, Any]]:
    """The process's metrics snapshot as a trailer record, so post-mortems
    carry the phase counters (commits, rollbacks, heals, wire/sync
    histograms) at time of abort next to the event ring. Never raises and
    never imports eagerly — the recorder must stay a leaf module that
    works during interpreter teardown."""
    try:
        from torchft_tpu import metrics

        return {"metrics": metrics.snapshot(), "ts": time.time()}
    except Exception:
        return None


def _trace_identity() -> "tuple[str, Optional[str]]":
    """(filename fragment, active incident id) from the trace plane's
    per-thread journal — identity so dumps correlate across hosts, the
    incident id so every process stamping the same quorum-wide event
    (deterministic id, tracing.incident_id) dumps under one name. Never
    raises and imports lazily — this module must stay a leaf that works
    during interpreter teardown."""
    try:
        from torchft_tpu import tracing

        journal = tracing.current()
        fragment = f"{tracing.sanitize(journal.replica_id)}_{journal.group_rank}"
        return fragment, journal.active_incident
    except Exception:  # noqa: BLE001
        return "proc_0", None


def dump(path: Optional[str] = None, reason: str = "") -> Optional[str]:
    """Writes the ring as JSON lines. With no ``path``, uses a fresh
    ``$TPUFT_FLIGHT_RECORDER/tpuft_fr_<replica>_<rank>_<pid>_<ns>
    [_<incident>].jsonl`` — or does nothing (returns None) when the env is
    unset. Returns the path. The last line is a ``{"metrics": ...}``
    trailer record (counter state at dump time)."""
    identity, incident = _trace_identity()
    if path is None:
        directory = os.environ.get(ENV_DIR, "")
        if not directory:
            return None
        os.makedirs(directory, exist_ok=True)
        # Unique per dump: a later failure must not overwrite the first
        # (root-cause) trace — the ring has usually wrapped by then.
        suffix = f"_{incident}" if incident else ""
        path = os.path.join(
            directory,
            f"tpuft_fr_{identity}_{os.getpid()}_{time.time_ns()}{suffix}.jsonl",
        )
    entries, truncated = _snapshot_meta()
    trailer = _metrics_trailer()
    # Atomic: a chaos kill mid-dump must never leave a truncated JSONL at
    # the final name (the soak asserts every surviving dump parses).
    tmp = f"{path}.tmp.{os.getpid()}"
    with _DUMP_LOCK:
        with open(tmp, "w") as f:
            if reason or truncated or incident:
                header: Dict[str, Any] = {"flight_recorder_dump_reason": reason}
                if truncated:
                    header["truncated"] = True
                if incident:
                    header["incident"] = incident
                f.write(json.dumps(header) + "\n")
            for entry in entries:
                f.write(json.dumps(entry) + "\n")
            if trailer is not None:
                f.write(json.dumps(trailer) + "\n")
        os.replace(tmp, path)
    return path


def dump_on_failure(source: str, reason: str) -> Optional[str]:
    """The abort/error hook: records the failure, then dumps iff
    ``TPUFT_FLIGHT_RECORDER`` is set (the reference's
    TRIGGER_FR_ON_ABORT semantics). Never raises — this runs on failure
    paths that must stay clean."""
    record(source, "failure", reason=reason)
    try:
        return dump(reason=f"{source}: {reason}")
    except Exception:  # noqa: BLE001 — failure hooks must never raise
        return None


def op_name_of(fn: Any) -> str:
    """Collective name from a closure defined inside a PG method:
    'ProcessGroupTCP.allreduce.<locals>.run' -> 'allreduce'."""
    qualname = getattr(fn, "__qualname__", "")
    parts = qualname.split(".")
    if len(parts) >= 3 and parts[-2] == "<locals>":
        return parts[-3]
    return qualname or repr(fn)
