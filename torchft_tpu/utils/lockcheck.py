"""Runtime lock-order detector for the Python coordination plane.

The native C++ plane has a TSAN build; this is the Python-side analogue for
the three interacting thread families (train loop, quorum thread, per-PG
op-worker). When enabled (``TPUFT_LOCK_CHECK=1``; on by default in the
``tests/ft_harness.py`` threads-as-replicas drills) it:

- shims ``threading.Lock`` / ``threading.RLock`` / ``threading.Condition``
  so locks *created at torchft_tpu (or tests/) call sites* record per-thread
  acquisition order — locks created by the stdlib or third-party code are
  left untouched (the creator's frame decides);
- maintains the global lock-order graph keyed by **creation site**
  (``file:line``), so every instance of e.g. the per-manager ``RWLock``
  shares one node, the classic lock-order-checker identity;
- fails the run (:class:`LockOrderError`) when an acquisition would close a
  cycle in that graph — the static witness of an ABBA deadlock — or when a
  commit barrier is entered with any instrumented lock held
  (:func:`check_barrier`, called by ``Manager.should_commit``: the
  "commit barriers run unlocked" invariant, CLAUDE.md architecture notes).

The ``RWLock`` (checkpointing/_rwlock.py) reports its *logical* reader/
writer holds through :func:`note_acquired` / :func:`note_released` — its
internal ``Condition`` is only held for microseconds and would hide the
actual hold window from the barrier check.

Static counterpart: rule R3 (lock-discipline) in
:mod:`torchft_tpu.analysis` proves the same invariant lexically; this
module catches the interleavings the AST cannot see.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set

from torchft_tpu.utils import schedules

__all__ = [
    "ENV",
    "LockOrderError",
    "enable",
    "disable",
    "enabled",
    "reset",
    "violations",
    "check_barrier",
    "note_acquired",
    "note_released",
    "creation_site",
]

ENV = "TPUFT_LOCK_CHECK"

_enabled = False
_orig: Dict[str, object] = {}

# The global lock-order graph: edge a -> b means "some thread held a lock
# created at site a while acquiring one created at site b".
_graph_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}
_violations: List[str] = []

_tls = threading.local()

_THIS_FILE = os.path.abspath(__file__)
_SCHEDULES_FILE = os.path.abspath(schedules.__file__)
_REPO_MARKERS = ("torchft_tpu", os.sep + "tests" + os.sep)


class LockOrderError(RuntimeError):
    """A lock-order cycle, or a lock held across a commit barrier."""


class _Held:
    __slots__ = ("obj", "site", "count")

    def __init__(self, obj: object, site: str) -> None:
        self.obj = obj
        self.site = site
        self.count = 1


def _held_stack() -> List[_Held]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = []
        _tls.held = held
    return held


def creation_site(skip: int = 1) -> str:
    """``file:line`` of the first caller frame outside this module."""
    frame = sys._getframe(skip)
    while frame is not None and os.path.abspath(frame.f_code.co_filename) == _THIS_FILE:
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    fname = frame.f_code.co_filename
    # Shorten to the repo-relative tail for readable reports.
    for marker in ("torchft_tpu", "tests"):
        idx = fname.rfind(os.sep + marker + os.sep)
        if idx >= 0:
            fname = fname[idx + 1 :]
            break
    return f"{fname}:{frame.f_lineno}"


def _is_instrumented_frame(skip: int = 2) -> bool:
    """True when the lock being created belongs to torchft_tpu or the test
    suite (stdlib/third-party creation sites stay uninstrumented).

    The schedule plane (utils/schedules.py) is explicitly EXCLUDED: the
    detector's note_* hooks are themselves schedule points, so an
    instrumented scheduler-internal condition would re-enter
    ``schedules.point`` while holding its own non-reentrant inner lock —
    a self-deadlock, not a finding."""
    frame = sys._getframe(skip)
    while frame is not None and os.path.abspath(frame.f_code.co_filename) == _THIS_FILE:
        frame = frame.f_back
    if frame is None:
        return False
    fname = frame.f_code.co_filename
    if os.path.abspath(fname) == _SCHEDULES_FILE:
        return False
    return any(marker in fname for marker in _REPO_MARKERS)


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst over _edges (caller holds _graph_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def note_acquired(obj: object, site: str, raise_on_cycle: bool = True) -> None:
    """Records that the calling thread now holds ``obj`` (created at
    ``site``). Adds order-graph edges from every other lock the thread
    holds; raises :class:`LockOrderError` (before recording the hold) if an
    edge would close a cycle. No-op when the detector is disabled."""
    if not _enabled:
        return
    # Lock acquisitions double as interleaving-explorer schedule points
    # (torchft_tpu.utils.schedules): free when no scheduler is active.
    schedules.point(f"lock.acquire:{site}")
    held = _held_stack()
    for rec in held:
        if rec.obj is obj:
            rec.count += 1  # reentrant (RLock / nested reader)
            return
    error = None
    for rec in held:
        if rec.site == site:
            # Two instances from one creation site (e.g. two managers'
            # RWLocks in a threads-as-replicas drill): no order is
            # expressible between them, so no edge.
            continue
        with _graph_lock:
            if site in _edges.get(rec.site, ()):
                continue
            back = _find_path(site, rec.site)
            if back is not None:
                msg = (
                    f"lock-order cycle: thread {threading.current_thread().name!r} "
                    f"acquires {site} while holding {rec.site}, but the "
                    f"established order is {' -> '.join(back)} -> {site}"
                )
                _violations.append(msg)
                error = LockOrderError(msg)
                break
            _edges.setdefault(rec.site, set()).add(site)
    if error is not None:
        raise error
    held.append(_Held(obj, site))


def note_released(obj: object) -> None:
    """Drops ``obj`` from the calling thread's held set (reentrant-aware).
    Unknown objects are ignored: the lock may predate enable()."""
    if _enabled:
        # Mirrors note_acquired's gate: releases double as schedule points
        # only while the detector is live.  Instrumented locks outlive
        # disable(), and their releases must not keep inflating the
        # explorer's schedule space after it (the held-set cleanup below
        # stays unconditional so a disable with locks held cannot strand
        # stale entries).
        schedules.point("lock.release")
    held = getattr(_tls, "held", None)
    if not held:
        return
    for index in range(len(held) - 1, -1, -1):
        if held[index].obj is obj:
            held[index].count -= 1
            if held[index].count <= 0:
                del held[index]
            return


def check_barrier(label: str) -> None:
    """Fails the run if the calling thread enters a commit barrier while
    holding any instrumented lock — the runtime form of the "commit
    barriers run unlocked" invariant (a barrier may apply a healing state
    dict, and peer serve threads need the state-dict read lock meanwhile;
    holding a lock here is a cross-replica deadlock waiting for the right
    interleaving)."""
    # Commit-barrier entry is a schedule point even with the lock detector
    # off — it is the highest-value preemption site the explorer has.
    schedules.point(f"lock.barrier:{label}")
    if not _enabled:
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    sites = ", ".join(rec.site for rec in held)
    msg = (
        f"lock held across commit barrier {label}: thread "
        f"{threading.current_thread().name!r} holds [{sites}] — barriers "
        "must run unlocked (CLAUDE.md invariant)"
    )
    _violations.append(msg)
    raise LockOrderError(msg)


def violations() -> List[str]:
    """Violations recorded so far (cycles + locked barriers)."""
    with _graph_lock:
        return list(_violations)


def reset() -> None:
    """Clears the order graph, violations, and this thread's held set."""
    with _graph_lock:
        _edges.clear()
        _violations.clear()
    _tls.held = []


# ---------------------------------------------------------------------------
# threading shims
# ---------------------------------------------------------------------------


class _InstrumentedLock:
    """Proxy over a real lock that reports acquire/release. On a detected
    cycle the inner lock is released before the error propagates, so a
    failing ``with`` statement cannot leak the hold."""

    def __init__(self, inner: object, site: str) -> None:
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)  # type: ignore[attr-defined]
        if ok:
            try:
                note_acquired(self, self._site)
            except BaseException:
                self._inner.release()  # type: ignore[attr-defined]
                raise
        return ok

    def release(self) -> None:
        note_released(self)
        self._inner.release()  # type: ignore[attr-defined]

    def locked(self) -> bool:
        return self._inner.locked()  # type: ignore[attr-defined]

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<instrumented {self._inner!r} from {self._site}>"


class _InstrumentedCondition:
    """Proxy over ``threading.Condition`` that tracks the underlying lock's
    hold, releasing it (for tracking purposes) across ``wait``/``wait_for``
    exactly as the real lock is released."""

    def __init__(self, lock: object = None, site: str = "") -> None:
        if isinstance(lock, _InstrumentedLock):
            lock = lock._inner
        self._inner = (
            _orig["Condition"](lock) if lock is not None else _orig["Condition"]()  # type: ignore[operator]
        )
        self._site = site

    def acquire(self, *args: object, **kwargs: object) -> bool:
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            try:
                note_acquired(self, self._site)
            except BaseException:
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        note_released(self)
        self._inner.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        note_released(self)
        try:
            return self._inner.wait(timeout)
        finally:
            # Re-adding edges that already exist never raises; a genuinely
            # new cycle on re-acquire is recorded without unwinding the
            # wait (the lock IS held again — report, don't corrupt).
            try:
                note_acquired(self, self._site)
            except LockOrderError:
                pass

    def wait_for(self, predicate, timeout: Optional[float] = None):
        note_released(self)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            try:
                note_acquired(self, self._site)
            except LockOrderError:
                pass

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<instrumented {self._inner!r} from {self._site}>"


def _lock_factory():
    inner = _orig["Lock"]()  # type: ignore[operator]
    if not _enabled or not _is_instrumented_frame():
        return inner
    return _InstrumentedLock(inner, creation_site(skip=2))


def _rlock_factory():
    inner = _orig["RLock"]()  # type: ignore[operator]
    if not _enabled or not _is_instrumented_frame():
        return inner
    return _InstrumentedLock(inner, creation_site(skip=2))


def _condition_factory(lock: object = None):
    if not _enabled or not _is_instrumented_frame():
        if isinstance(lock, _InstrumentedLock):
            lock = lock._inner
        return _orig["Condition"](lock) if lock is not None else _orig["Condition"]()  # type: ignore[operator]
    return _InstrumentedCondition(lock, creation_site(skip=2))


def enable() -> None:
    """Patches the ``threading`` lock constructors (idempotent). Only locks
    created *after* this call, from torchft_tpu/tests frames, are
    instrumented — module-level singletons created at import time stay
    invisible, which is the intended noise bound."""
    global _enabled
    if _enabled:
        return
    if not _orig:
        _orig["Lock"] = threading.Lock
        _orig["RLock"] = threading.RLock
        _orig["Condition"] = threading.Condition
    _enabled = True
    threading.Lock = _lock_factory  # type: ignore[misc,assignment]
    threading.RLock = _rlock_factory  # type: ignore[misc,assignment]
    threading.Condition = _condition_factory  # type: ignore[misc,assignment]


def disable() -> None:
    """Restores the original constructors. Already-instrumented locks keep
    working (their note_* calls become no-ops)."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    threading.Lock = _orig["Lock"]  # type: ignore[misc,assignment]
    threading.RLock = _orig["RLock"]  # type: ignore[misc,assignment]
    threading.Condition = _orig["Condition"]  # type: ignore[misc,assignment]


def enabled() -> bool:
    return _enabled


def maybe_enable_from_env(default: str = "0") -> bool:
    """Enables the detector when ``$TPUFT_LOCK_CHECK`` (default: ``default``)
    is truthy; returns the resulting enabled state."""
    if os.environ.get(ENV, default) not in ("0", "", "false", "no"):
        enable()
    return _enabled
