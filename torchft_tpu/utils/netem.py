"""Emulated-DCN network shim for single-host benchmarking.

This box (and any single-host CI) can only produce loopback numbers for
cross-replica traffic, which says nothing about the design claims that
motivate streaming DiLoCo and the int4 wire — hiding outer-sync latency
and halving bytes only MATTER under non-zero RTT and bounded bandwidth
(the reference's whole DiLoCo pitch, reference local_sgd.py:176-568
design comments). This shim injects both at the Python wire choke points
(ProcessGroupTCP sends, HTTP checkpoint chunk serves) so a loopback bench
can sweep a latency-tolerance curve.

Configuration, in precedence order:

- :func:`configure` (what benches call per sweep point), or
- env at first use: ``TPUFT_EMULATED_RTT_MS`` (per-message one-way delay
  = RTT/2) and ``TPUFT_EMULATED_GBPS`` (serialization time =
  bytes / bandwidth).

Disabled (the default) costs one attribute load + truthiness test per
message. This is a measurement shim, not a simulator: delays are sleeps
on the sending side, so concurrent flows each pay their own
serialization — a per-flow bandwidth model, not a shared-link one.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Optional, Tuple

# (one_way_delay_s, seconds_per_byte); None = not yet resolved from env.
_config: Optional[Tuple[float, float]] = None

# Response header a netem-paced HTTP server sets on bodies it already
# charged the emulated link for (pace_latency + PacingWriter). A paced
# CLIENT fetch seam (serving/_wire.py) skips its response-leg charge when
# it sees this, so a hop is never double-billed no matter which side of
# it carries the shim.
PACED_HEADER = "X-TPUFT-Link-Paced"


def configure(rtt_ms: float = 0.0, gbps: float = 0.0) -> None:
    """Set the emulated link for this process; zeros disable."""
    global _config
    one_way = max(rtt_ms, 0.0) / 2000.0
    spb = 8.0 / (gbps * 1e9) if gbps > 0 else 0.0
    _config = (one_way, spb)


def _resolve() -> Tuple[float, float]:
    global _config
    if _config is None:
        configure(
            float(os.environ.get("TPUFT_EMULATED_RTT_MS", "0") or 0.0),
            float(os.environ.get("TPUFT_EMULATED_GBPS", "0") or 0.0),
        )
    assert _config is not None
    return _config


def enabled() -> bool:
    delay, spb = _resolve()
    return delay > 0.0 or spb > 0.0


def emulated_device_sync(rtt_ms: float, ack_threshold_s: float = 1e-3):
    """A ``jax.block_until_ready`` replacement that charges the remote-
    device readiness cost a tunneled accelerator pays (env
    ``TPUFT_EMULATED_DEVICE_RTT_MS`` when ``rtt_ms`` is 0), modeled on the
    relay behavior CLAUDE.md documents and BENCH_r05 measured: a readiness
    call on IN-FLIGHT work costs completion plus one full round trip
    (~73 ms ``device_sync_rtt_ms`` — observed as a flat +RTT per step
    across a 16x model-size change, so the round trip does NOT hide under
    remaining compute), while a call on work the relay has already acked
    is ~free (~0.05 ms). The shim distinguishes the two by how long the
    real (local, ~instant-on-complete) wait took: longer than
    ``ack_threshold_s`` means the work was still in flight, and the
    response round trip is charged after completion.

    Shimming ``optim._bound_device`` with this reproduces, deterministically
    and without the relay, exactly why the pipelined-commit mode wins: it
    only ever probes the PREVIOUS step's (completed, acked) work, where
    the serialized orderings probe in-flight work every step. A
    measurement shim for the emulated-DCN bench, not a simulator."""
    if not rtt_ms:
        rtt_ms = float(os.environ.get("TPUFT_EMULATED_DEVICE_RTT_MS", "0") or 0.0)
    rtt_s = max(rtt_ms, 0.0) / 1000.0

    def sync(x: Any) -> Any:
        import jax

        t0 = time.monotonic()
        out = jax.block_until_ready(x)
        if rtt_s and time.monotonic() - t0 > ack_threshold_s:
            time.sleep(rtt_s)
        return out

    return sync


def pace(nbytes: int) -> None:
    """Sleep for the emulated link's share of sending ``nbytes`` as one
    message: RTT/2 of propagation + bytes/bandwidth of serialization."""
    delay, spb = _resolve()
    d = delay + nbytes * spb
    if d > 0.0:
        time.sleep(d)


def pace_deadline(nbytes: int, deadline: float) -> None:
    """:func:`pace`, bounded by an absolute monotonic ``deadline``: sleeps
    at most the remaining time and raises ``socket.timeout`` when the
    emulated link cannot deliver the message in time — the failure a real
    link of this speed would produce under the caller's op timeout.
    Deadline-bounded wire paths (ProcessGroupTCP sends) must use this so
    an emulated slow link cannot stall an op past its deadline."""
    delay, spb = _resolve()
    d = delay + nbytes * spb
    if d <= 0.0:
        return
    remaining = deadline - time.monotonic()
    if d > max(remaining, 0.0):
        time.sleep(max(remaining, 0.0))
        raise socket.timeout("emulated link exceeded the op deadline")
    time.sleep(d)


def pace_latency() -> None:
    """The propagation half only (RTT/2) — charge once per message when
    the serialization share is paced incrementally via a PacingWriter."""
    delay, _ = _resolve()
    if delay > 0.0:
        time.sleep(delay)


class PacingWriter:
    """File-like wrapper that charges the emulated link's serialization
    time interleaved with the actual writes, in bounded slices — one
    up-front sleep for a huge body would hold the wire silent longer than
    a per-recv inactivity timeout, a failure a real link of the same
    bandwidth (which trickles bytes) would not produce. Wrap only when
    :func:`enabled`; pace latency separately via :func:`pace_latency`."""

    _SLICE = 8 << 20  # 8 MiB: bandwidth sleep per write stays ~sub-second

    def __init__(self, raw: Any) -> None:
        self._raw = raw

    def write(self, data: Any) -> int:
        _, spb = _resolve()
        view = memoryview(data)
        for off in range(0, max(len(view), 1), self._SLICE):
            part = view[off : off + self._SLICE]
            if spb > 0.0 and len(part):
                time.sleep(len(part) * spb)
            self._raw.write(part)
        return len(view)

    def flush(self) -> None:
        self._raw.flush()


class TCPFront:
    """Shared scaffolding for wire-front proxies placed ahead of a real
    server (latency injection here; fault injection in the lighthouse
    tests): target address parsing, the listener + accept loop, and
    per-connection handler threads. Subclasses implement
    :meth:`handle`."""

    def __init__(self, target_addr: str) -> None:
        host, _, port = target_addr.rpartition(":")
        self.target = (host.strip("[]") or "127.0.0.1", int(port))
        self._stop = False
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def address(self) -> str:
        return f"127.0.0.1:{self._srv.getsockname()[1]}"

    @property
    def stopping(self) -> bool:
        return self._stop

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self.handle, args=(conn,), daemon=True).start()

    def handle(self, conn: socket.socket) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        self._stop = True
        self._thread.join(timeout=2)
        self._srv.close()


class LatencyProxy(TCPFront):
    """Byte-level proxy that sleeps RTT/2 before forwarding each burst in
    each direction — a DCN hop in front of a control-plane server. Framing
    agnostic; used by the emulated-DCN bench to measure quorum latency
    sensitivity."""

    def __init__(self, target_addr: str, rtt_ms: float) -> None:
        self._one_way = max(rtt_ms, 0.0) / 2000.0
        super().__init__(target_addr)

    def handle(self, conn: socket.socket) -> None:
        try:
            up = socket.create_connection(self.target, timeout=10)
        except OSError:
            conn.close()
            return

        def copy(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    if self._one_way:
                        time.sleep(self._one_way)
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        t = threading.Thread(target=copy, args=(up, conn), daemon=True)
        t.start()
        copy(conn, up)
        t.join(timeout=10)
        conn.close()
        up.close()
