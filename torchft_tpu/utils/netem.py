"""Emulated-DCN network shim for single-host benchmarking.

This box (and any single-host CI) can only produce loopback numbers for
cross-replica traffic, which says nothing about the design claims that
motivate streaming DiLoCo and the int4 wire — hiding outer-sync latency
and halving bytes only MATTER under non-zero RTT and bounded bandwidth
(the reference's whole DiLoCo pitch, reference local_sgd.py:176-568
design comments). This shim injects both at the Python wire choke points
(ProcessGroupTCP sends, HTTP checkpoint chunk serves) so a loopback bench
can sweep a latency-tolerance curve.

Configuration, in precedence order:

- :func:`configure` / :func:`configure_topology` (what benches call per
  sweep point), or
- env at first use: ``TPUFT_EMULATED_RTT_MS`` (per-message one-way delay
  = RTT/2) and ``TPUFT_EMULATED_GBPS`` (serialization time =
  bytes / bandwidth) for the single global link, plus optionally a
  per-(src,dst)-region link MATRIX:

  - ``TPUFT_EMULATED_TOPOLOGY="r0=us,r1=us,r2=eu[,*=us]"`` assigns a
    region per replica id (stable id — the part before the first ``:``;
    ``*`` is the default region for unlisted replicas);
  - ``TPUFT_EMULATED_LINK_<SRC>_<DST>="rtt_ms,gbps"`` sets one DIRECTED
    pair's link (region names uppercased in the env name, so they must
    not contain ``_``); ``TPUFT_EMULATED_LINK_LOCAL`` /
    ``TPUFT_EMULATED_LINK_CROSS`` are the intra-/cross-region defaults
    for pairs without an explicit entry. Any pair still unresolved falls
    back to the global single-link envs — with no topology configured
    at all, behavior is byte-identical to the single-link shim (the
    1-region degenerate case).

  A process learns its own region from ``TPUFT_EMULATED_REGION`` or from
  :func:`set_local_replica_id` (the manager calls it with its replica
  id); wire seams that know the PEER's region (the heal chunk server
  reads the joiner's ``?region=`` tag) pace per the (local, peer) link.

Disabled (the default) costs one attribute load + truthiness test per
message. This is a measurement shim, not a simulator: delays are sleeps
on the sending side, so concurrent flows each pay their own
serialization — a per-flow bandwidth model, not a shared-link one.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# (one_way_delay_s, seconds_per_byte); None = not yet resolved from env.
_config: Optional[Tuple[float, float]] = None

ENV_TOPOLOGY = "TPUFT_EMULATED_TOPOLOGY"
ENV_REGION = "TPUFT_EMULATED_REGION"
LINK_ENV_PREFIX = "TPUFT_EMULATED_LINK_"

# Response header a netem-paced HTTP server sets on bodies it already
# charged the emulated link for (pace_latency + PacingWriter). A paced
# CLIENT fetch seam (serving/_wire.py) skips its response-leg charge when
# it sees this, so a hop is never double-billed no matter which side of
# it carries the shim.
PACED_HEADER = "X-TPUFT-Link-Paced"


def configure(rtt_ms: float = 0.0, gbps: float = 0.0) -> None:
    """Set the emulated link for this process; zeros disable."""
    global _config
    one_way = max(rtt_ms, 0.0) / 2000.0
    spb = 8.0 / (gbps * 1e9) if gbps > 0 else 0.0
    _config = (one_way, spb)


def _resolve() -> Tuple[float, float]:
    global _config
    if _config is None:
        configure(
            float(os.environ.get("TPUFT_EMULATED_RTT_MS", "0") or 0.0),
            float(os.environ.get("TPUFT_EMULATED_GBPS", "0") or 0.0),
        )
    assert _config is not None
    return _config


class _Topology:
    """Parsed region map + directed link matrix. Pure data; all lookups
    fall back (pair -> intra/cross default -> global single link) so a
    partially-specified matrix is always servable."""

    __slots__ = (
        "regions", "default_region", "links", "intra_default",
        "cross_default", "self_region", "errors",
    )

    def __init__(self) -> None:
        self.regions: Dict[str, str] = {}
        self.default_region: Optional[str] = None
        # (src_region, dst_region) -> (one_way_delay_s, seconds_per_byte)
        self.links: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self.intra_default: Optional[Tuple[float, float]] = None
        self.cross_default: Optional[Tuple[float, float]] = None
        self.self_region: Optional[str] = None
        self.errors: List[str] = []

    def region_names(self) -> List[str]:
        names = set(self.regions.values())
        if self.default_region:
            names.add(self.default_region)
        return sorted(names)

    def any_paced(self) -> bool:
        for pair in list(self.links.values()) + [
            link
            for link in (self.intra_default, self.cross_default)
            if link is not None
        ]:
            if pair[0] > 0.0 or pair[1] > 0.0:
                return True
        return False


# None = no topology configured; unresolved until first use.
_topology_cache: Optional[_Topology] = None
_topology_resolved = False
_local_replica_id: Optional[str] = None


def _parse_link(raw: str) -> Tuple[float, float]:
    """``"rtt_ms,gbps"`` (``:`` separator accepted) -> (delay_s, spb)."""
    parts = [p.strip() for p in raw.replace(":", ",").split(",")]
    rtt_ms = float(parts[0] or 0.0)
    gbps = float(parts[1] or 0.0) if len(parts) > 1 and parts[1] else 0.0
    return (max(rtt_ms, 0.0) / 2000.0, 8.0 / (gbps * 1e9) if gbps > 0 else 0.0)


def _resolve_topology() -> Optional[_Topology]:
    global _topology_cache, _topology_resolved
    if _topology_resolved:
        return _topology_cache
    topo = _Topology()
    raw = os.environ.get(ENV_TOPOLOGY, "").strip()
    for token in filter(None, (t.strip() for t in raw.split(","))):
        rid, sep, region = token.partition("=")
        if not sep or not region.strip():
            topo.errors.append(f"bad {ENV_TOPOLOGY} token {token!r}")
            continue
        rid, region = rid.strip(), region.strip().lower()
        if rid == "*":
            topo.default_region = region
        else:
            topo.regions[rid] = region
    for name in sorted(os.environ):
        if not name.startswith(LINK_ENV_PREFIX):
            continue
        try:
            link = _parse_link(os.environ[name])
        except ValueError:
            topo.errors.append(f"unparseable link {name}={os.environ[name]!r}")
            continue
        tail = name[len(LINK_ENV_PREFIX):]
        if tail == "LOCAL":
            topo.intra_default = link
        elif tail == "CROSS":
            topo.cross_default = link
        else:
            src, sep, dst = tail.partition("_")
            if not sep or not src or not dst or "_" in dst:
                topo.errors.append(
                    f"link env {name} is not <SRC>_<DST> (region names "
                    "must not contain '_')"
                )
                continue
            topo.links[(src.lower(), dst.lower())] = link
    region = os.environ.get(ENV_REGION, "").strip().lower()
    if region:
        topo.self_region = region
    has_any = bool(
        topo.regions or topo.default_region or topo.links
        or topo.intra_default or topo.cross_default or topo.self_region
    )
    _topology_cache = topo if has_any else None
    _topology_resolved = True
    return _topology_cache


def configure_topology(
    regions: Optional[Dict[str, str]] = None,
    links: Optional[Dict[Tuple[str, str], Tuple[float, float]]] = None,
    intra: Optional[Tuple[float, float]] = None,
    cross: Optional[Tuple[float, float]] = None,
    self_region: Optional[str] = None,
    default_region: Optional[str] = None,
) -> None:
    """Programmatic topology for benches/tests: ``links``/``intra``/
    ``cross`` take (rtt_ms, gbps) pairs. Passing nothing installs an
    EMPTY topology (region-blind — the single-link degenerate case);
    call :func:`reset_topology` to go back to env resolution."""
    global _topology_cache, _topology_resolved
    has_any = bool(regions or links or intra or cross or self_region)
    if not has_any:
        _topology_cache = None
        _topology_resolved = True
        return
    topo = _Topology()
    topo.regions = {k: v.lower() for k, v in (regions or {}).items()}
    topo.default_region = default_region.lower() if default_region else None
    topo.links = {
        (s.lower(), d.lower()): _parse_link(f"{rtt},{gbps}")
        for (s, d), (rtt, gbps) in (links or {}).items()
    }
    topo.intra_default = _parse_link(f"{intra[0]},{intra[1]}") if intra else None
    topo.cross_default = _parse_link(f"{cross[0]},{cross[1]}") if cross else None
    topo.self_region = self_region.lower() if self_region else None
    _topology_cache = topo
    _topology_resolved = True


def reset_topology() -> None:
    """Forget any parsed/programmatic topology; env re-resolves at next use."""
    global _topology_cache, _topology_resolved
    _topology_cache = None
    _topology_resolved = False


def topology_enabled() -> bool:
    return _resolve_topology() is not None


def set_local_replica_id(replica_id: Optional[str]) -> None:
    """Tell the shim who THIS process is (the manager calls it with its
    replica id) so :func:`local_region` can answer from the topology map.
    Cheap and unconditional — a no-op without a topology."""
    global _local_replica_id
    _local_replica_id = replica_id


def region_of(replica_id: Optional[str]) -> Optional[str]:
    """The region the topology assigns to ``replica_id`` (exact id first,
    then the stable prefix before the first ``:``), or None."""
    topo = _resolve_topology()
    if topo is None or not replica_id:
        return None
    if replica_id in topo.regions:
        return topo.regions[replica_id]
    stable = replica_id.split(":", 1)[0]
    return topo.regions.get(stable, topo.default_region)


def local_region() -> Optional[str]:
    """This process's own region: explicit (``TPUFT_EMULATED_REGION`` /
    ``configure_topology(self_region=...)``) first, else derived from the
    replica id registered via :func:`set_local_replica_id`."""
    topo = _resolve_topology()
    if topo is None:
        return None
    return topo.self_region or region_of(_local_replica_id)


def link_params(
    src_region: Optional[str], dst_region: Optional[str]
) -> Tuple[float, float]:
    """(one_way_delay_s, seconds_per_byte) for the DIRECTED (src, dst)
    region pair: exact pair entry -> intra/cross default -> the global
    single link. Either side unknown degrades to the global link."""
    topo = _resolve_topology()
    if topo is None or src_region is None or dst_region is None:
        return _resolve()
    src, dst = src_region.lower(), dst_region.lower()
    link = topo.links.get((src, dst))
    if link is not None:
        return link
    fallback = topo.intra_default if src == dst else topo.cross_default
    return fallback if fallback is not None else _resolve()


def _link_for_peer(peer_region: Optional[str]) -> Tuple[float, float]:
    """Sender-side link choice: the (local, peer) pair when the peer's
    region is known, the global single link otherwise."""
    if peer_region is None or not topology_enabled():
        return _resolve()
    return link_params(local_region(), peer_region)


def describe_topology() -> Dict[str, Any]:
    """Parse summary for the doctor's WARN-never-FAIL topology probe."""
    topo = _resolve_topology()
    if topo is None:
        return {"configured": False}
    names = topo.region_names()
    return {
        "configured": True,
        "regions": dict(topo.regions),
        "default_region": topo.default_region,
        "region_names": names,
        "single_region": len(names) <= 1,
        "num_links": len(topo.links),
        "has_intra_default": topo.intra_default is not None,
        "has_cross_default": topo.cross_default is not None,
        "self_region": local_region(),
        "errors": list(topo.errors),
    }


def enabled() -> bool:
    delay, spb = _resolve()
    if delay > 0.0 or spb > 0.0:
        return True
    topo = _resolve_topology()
    return topo is not None and topo.any_paced()


def emulated_device_sync(rtt_ms: float, ack_threshold_s: float = 1e-3):
    """A ``jax.block_until_ready`` replacement that charges the remote-
    device readiness cost a tunneled accelerator pays (env
    ``TPUFT_EMULATED_DEVICE_RTT_MS`` when ``rtt_ms`` is 0), modeled on the
    relay behavior CLAUDE.md documents and BENCH_r05 measured: a readiness
    call on IN-FLIGHT work costs completion plus one full round trip
    (~73 ms ``device_sync_rtt_ms`` — observed as a flat +RTT per step
    across a 16x model-size change, so the round trip does NOT hide under
    remaining compute), while a call on work the relay has already acked
    is ~free (~0.05 ms). The shim distinguishes the two by how long the
    real (local, ~instant-on-complete) wait took: longer than
    ``ack_threshold_s`` means the work was still in flight, and the
    response round trip is charged after completion.

    Shimming ``optim._bound_device`` with this reproduces, deterministically
    and without the relay, exactly why the pipelined-commit mode wins: it
    only ever probes the PREVIOUS step's (completed, acked) work, where
    the serialized orderings probe in-flight work every step. A
    measurement shim for the emulated-DCN bench, not a simulator."""
    if not rtt_ms:
        rtt_ms = float(os.environ.get("TPUFT_EMULATED_DEVICE_RTT_MS", "0") or 0.0)
    rtt_s = max(rtt_ms, 0.0) / 1000.0

    def sync(x: Any) -> Any:
        import jax

        t0 = time.monotonic()
        out = jax.block_until_ready(x)
        if rtt_s and time.monotonic() - t0 > ack_threshold_s:
            time.sleep(rtt_s)
        return out

    return sync


def pace(nbytes: int, peer_region: Optional[str] = None) -> None:
    """Sleep for the emulated link's share of sending ``nbytes`` as one
    message: RTT/2 of propagation + bytes/bandwidth of serialization.
    ``peer_region`` selects the (local, peer) link from the topology
    matrix when known; None keeps the global single link."""
    delay, spb = _link_for_peer(peer_region)
    d = delay + nbytes * spb
    if d > 0.0:
        time.sleep(d)


def pace_deadline(
    nbytes: int, deadline: float, peer_region: Optional[str] = None
) -> None:
    """:func:`pace`, bounded by an absolute monotonic ``deadline``: sleeps
    at most the remaining time and raises ``socket.timeout`` when the
    emulated link cannot deliver the message in time — the failure a real
    link of this speed would produce under the caller's op timeout.
    Deadline-bounded wire paths (ProcessGroupTCP sends) must use this so
    an emulated slow link cannot stall an op past its deadline."""
    delay, spb = _link_for_peer(peer_region)
    d = delay + nbytes * spb
    if d <= 0.0:
        return
    remaining = deadline - time.monotonic()
    if d > max(remaining, 0.0):
        time.sleep(max(remaining, 0.0))
        raise socket.timeout("emulated link exceeded the op deadline")
    time.sleep(d)


def pace_latency(peer_region: Optional[str] = None) -> None:
    """The propagation half only (RTT/2) — charge once per message when
    the serialization share is paced incrementally via a PacingWriter."""
    delay, _ = _link_for_peer(peer_region)
    if delay > 0.0:
        time.sleep(delay)


class PacingWriter:
    """File-like wrapper that charges the emulated link's serialization
    time interleaved with the actual writes, in bounded slices — one
    up-front sleep for a huge body would hold the wire silent longer than
    a per-recv inactivity timeout, a failure a real link of the same
    bandwidth (which trickles bytes) would not produce. Wrap only when
    :func:`enabled`; pace latency separately via :func:`pace_latency`.
    ``peer_region`` pins the topology link once at construction (the peer
    does not move mid-body)."""

    _SLICE = 8 << 20  # 8 MiB: bandwidth sleep per write stays ~sub-second

    def __init__(self, raw: Any, peer_region: Optional[str] = None) -> None:
        self._raw = raw
        self._peer_region = peer_region

    def write(self, data: Any) -> int:
        _, spb = _link_for_peer(self._peer_region)
        view = memoryview(data)
        for off in range(0, max(len(view), 1), self._SLICE):
            part = view[off : off + self._SLICE]
            if spb > 0.0 and len(part):
                time.sleep(len(part) * spb)
            self._raw.write(part)
        return len(view)

    def flush(self) -> None:
        self._raw.flush()


class TCPFront:
    """Shared scaffolding for wire-front proxies placed ahead of a real
    server (latency injection here; fault injection in the lighthouse
    tests): target address parsing, the listener + accept loop, and
    per-connection handler threads. Subclasses implement
    :meth:`handle`."""

    def __init__(self, target_addr: str) -> None:
        host, _, port = target_addr.rpartition(":")
        self.target = (host.strip("[]") or "127.0.0.1", int(port))
        self._stop = False
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def address(self) -> str:
        return f"127.0.0.1:{self._srv.getsockname()[1]}"

    @property
    def stopping(self) -> bool:
        return self._stop

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self.handle, args=(conn,), daemon=True).start()

    def handle(self, conn: socket.socket) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        self._stop = True
        self._thread.join(timeout=2)
        self._srv.close()


class LatencyProxy(TCPFront):
    """Byte-level proxy that sleeps RTT/2 before forwarding each burst in
    each direction — a DCN hop in front of a control-plane server. Framing
    agnostic; used by the emulated-DCN bench to measure quorum latency
    sensitivity."""

    def __init__(self, target_addr: str, rtt_ms: float) -> None:
        self._one_way = max(rtt_ms, 0.0) / 2000.0
        super().__init__(target_addr)

    def handle(self, conn: socket.socket) -> None:
        try:
            up = socket.create_connection(self.target, timeout=10)
        except OSError:
            conn.close()
            return

        def copy(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    if self._one_way:
                        time.sleep(self._one_way)
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        t = threading.Thread(target=copy, args=(up, conn), daemon=True)
        t.start()
        copy(conn, up)
        t.join(timeout=10)
        conn.close()
        up.close()
