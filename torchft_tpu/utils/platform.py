"""Platform selection helpers."""

from __future__ import annotations

import os

__all__ = ["honor_jax_platforms_env"]


def honor_jax_platforms_env() -> None:
    """Applies $JAX_PLATFORMS via jax.config before backend init.

    Some machines pin the platform list in jax's config from a sitecustomize,
    which silently overrides the environment variable; applying the env value
    through the config restores the expected contract. No-op once a backend
    is initialized or when the variable is unset."""
    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            jax.config.update("jax_platforms", platforms)
        except RuntimeError:
            pass
