"""Platform selection helpers."""

from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["honor_jax_platforms_env", "on_tpu", "probe_accelerator"]


def on_tpu() -> bool:
    """True when the default device's PLATFORM is TPU.

    The backend NAME can differ (e.g. the remote-tunnel backend is "axon"
    while its device platform is "tpu"), and only the platform says whether
    Mosaic can compile Pallas kernels — every TPU-vs-elsewhere dispatch
    must use this check, held here once."""
    import jax

    return jax.devices()[0].platform == "tpu"


def probe_accelerator(timeout: float = 180.0) -> bool:
    """True iff the attached accelerator completes a full
    compile→execute→fetch round trip within ``timeout`` seconds.

    Runs in a disposable subprocess because the remote-chip relay on some
    machines has failure modes that WEDGE rather than error: PJRT init can
    hang for hours, or ``jax.devices()`` lists the chip while the first
    compile/execute never completes. Probing in-process would hang the
    caller — exactly what this function exists to prevent. stdout/stderr go
    to DEVNULL (not pipes): a wedged init can leave a tunnel-helper
    grandchild holding inherited pipe fds, and draining them after the
    timeout kill would hang forever."""
    probe_src = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((128, 128), jnp.bfloat16);"
        "y = jax.jit(lambda a: a @ a)(x);"
        "assert float(y[0, 0]) == 128.0"
    )
    try:
        probe = subprocess.run(
            [sys.executable, "-c", probe_src],
            timeout=timeout,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def honor_jax_platforms_env() -> None:
    """Applies $JAX_PLATFORMS via jax.config before backend init.

    Some machines pin the platform list in jax's config from a sitecustomize,
    which silently overrides the environment variable; applying the env value
    through the config restores the expected contract. No-op once a backend
    is initialized or when the variable is unset."""
    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            jax.config.update("jax_platforms", platforms)
        except RuntimeError:
            pass
