"""Tracing/profiling spans.

Role-equivalent of the reference's ``torch.profiler.record_function`` spans
on every manager phase (manager.py:385-827) and the ``_time``/``_timeit``
transfer logs (http_transport.py:31-36): here spans emit
``jax.profiler.TraceAnnotation`` markers, which show up on the TensorBoard
trace viewer timeline when a ``jax.profiler.trace`` capture is active, and
optionally log wall time when ``TPUFT_TRACE_LOG`` is set.
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager
from typing import Generator, Iterator

logger = logging.getLogger("torchft_tpu.trace")

_LOG_SPANS = os.environ.get("TPUFT_TRACE_LOG", "") == "1"


@contextmanager
def trace_span(name: str) -> Generator[None, None, None]:
    """Marks a region on the jax profiler timeline (no-op cost when no
    capture is active)."""
    try:
        import jax.profiler

        annotation = jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001  — profiling must never break training
        annotation = None
    start = time.monotonic() if _LOG_SPANS else 0.0
    if annotation is not None:
        annotation.__enter__()
    try:
        yield
    finally:
        if annotation is not None:
            annotation.__exit__(None, None, None)
        if _LOG_SPANS:
            logger.info("%s took %.3fms", name, (time.monotonic() - start) * 1000)


@contextmanager
def timed(name: str) -> Iterator[None]:
    """Always-on wall-time log for transfer-sized operations."""
    start = time.monotonic()
    try:
        yield
    finally:
        logger.info("%s took %.3fs", name, time.monotonic() - start)
