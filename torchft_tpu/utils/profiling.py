"""Tracing/profiling spans + chrome-trace export.

Role-equivalent of the reference's ``torch.profiler.record_function`` spans
on every manager phase (manager.py:385-827), the ``_time``/``_timeit``
transfer logs (http_transport.py:31-36), and its chrome-trace export loops
(train_ddp.py:159-174): spans emit ``jax.profiler.TraceAnnotation`` markers
(TensorBoard/perfetto timeline when a ``jax.profiler.trace`` capture is
active), optionally log wall time when ``TPUFT_TRACE_LOG`` is set, and —
when a :func:`chrome_trace` capture is active — record begin/end events
into a self-contained ``trace.json`` loadable in ``chrome://tracing`` or
https://ui.perfetto.dev.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Generator, Iterator, List, Optional

logger = logging.getLogger("torchft_tpu.trace")

_LOG_SPANS = os.environ.get("TPUFT_TRACE_LOG", "") == "1"

class _ChromeCapture:
    """One active chrome-trace capture: the event list plus per-thread
    bookkeeping so each thread's FIRST span also emits a ``thread_name``
    metadata ("M") event — without it the pipelined-commit spans (which
    resolve on the tpuft_quorum executor and the PG op-worker threads)
    interleave as anonymous numeric tids in chrome://tracing."""

    def __init__(self) -> None:
        self.events: List[dict] = []
        self.lock = threading.Lock()
        self._named_tids: set = set()

    def add_span(self, name: str, start: float, elapsed: float, args: dict) -> None:
        thread = threading.current_thread()
        tid = threading.get_ident() % 2**31
        event = {
            "name": name,
            "ph": "X",
            "ts": start * 1e6,
            "dur": elapsed * 1e6,
            "pid": os.getpid(),
            "tid": tid,
            "cat": "tpuft",
        }
        if args:
            event["args"] = args
        with self.lock:
            if tid not in self._named_tids:
                self._named_tids.add(tid)
                self.events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": os.getpid(),
                        "tid": tid,
                        "args": {"name": thread.name},
                    }
                )
            self.events.append(event)


# Active chrome-trace capture, or None.
_CHROME: Optional[_ChromeCapture] = None


@contextmanager
def chrome_trace(path: str) -> Generator[None, None, None]:
    """Captures every :func:`trace_span` in the with-body as chrome-trace
    "X" (complete) events — plus one ``thread_name`` metadata event per
    emitting thread — and writes them to ``path`` on exit. Captures may
    nest/overlap (the previous capture is restored on exit); spans still
    open on other threads when the capture ends record into the old list
    harmlessly (they are not in the written file)."""
    global _CHROME
    capture = _ChromeCapture()
    previous = _CHROME
    _CHROME = capture
    try:
        yield
    finally:
        _CHROME = previous
        with capture.lock:
            snapshot = list(capture.events)
        # Fleet-merge metadata: stamp the trace plane's replica identity
        # and last store-sampled clock offset onto the capture, so a
        # single-process chrome trace drops cleanly into a merged fleet
        # timeline (scripts/fleet_trace.py shifts by clock_offset_ms and
        # keys tracks by replica_id) instead of arriving as an anonymous
        # pid with an unaligned clock.
        other_data: dict = {}
        try:
            from torchft_tpu import tracing

            journal = tracing.current()
            offset_ms = (
                round(journal.clock_offset_s * 1e3, 3)
                if journal.clock_offset_s is not None
                else None
            )
            other_data = {
                "replica_id": journal.replica_id,
                "group_rank": journal.group_rank,
                "clock_offset_ms": offset_ms,
            }
            snapshot.insert(
                0,
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": os.getpid(),
                    "args": {
                        "name": f"{journal.replica_id}/{journal.group_rank}"
                    },
                },
            )
            for event in snapshot:
                if event.get("ph") == "X":
                    event.setdefault("args", {}).setdefault(
                        "replica_id", journal.replica_id
                    )
        except Exception:  # noqa: BLE001 — profiling must never break training
            pass
        with open(path, "w") as f:
            json.dump(
                {
                    "traceEvents": snapshot,
                    "displayTimeUnit": "ms",
                    "otherData": other_data,
                },
                f,
            )
        logger.info(
            "chrome trace with %d events written to %s", len(snapshot), path
        )


@contextmanager
def trace_span(name: str, **args: "int | float | str") -> Generator[None, None, None]:
    """Marks a region on the jax profiler timeline (no-op cost when no
    capture is active) and on any active :func:`chrome_trace` capture.
    ``args`` (e.g. ``step=``, ``quorum_id=``) land in the chrome event's
    ``args`` dict so a merged kill/heal trace stays correlatable across
    the train-loop / quorum / op-worker threads."""
    try:
        import jax.profiler

        annotation = jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001  — profiling must never break training
        annotation = None
    chrome = _CHROME
    start = time.monotonic() if (_LOG_SPANS or chrome is not None) else 0.0
    if annotation is not None:
        annotation.__enter__()
    try:
        yield
    finally:
        if annotation is not None:
            annotation.__exit__(None, None, None)
        elapsed = time.monotonic() - start
        if chrome is not None:
            chrome.add_span(name, start, elapsed, args)
        if _LOG_SPANS:
            logger.info("%s took %.3fms", name, elapsed * 1000)


def heal_wall_times(kill_t: "float | None", commit_times: dict) -> "dict | None":
    """Kill → first-committed-step wall time per replica group, the
    operator-facing recovery number (BASELINE.md north stars time-bound
    what steps_lost_per_kill only counts). ``commit_times`` maps group
    index → monotonic commit timestamps; group 0 is labeled the survivor
    and group 1 the joiner (the drills' kill target), higher groups keep
    an index label. Returns None when no kill happened; a group with no
    commit after the kill reports None for its role."""
    if kill_t is None:
        return None
    out = {}
    for idx, times in sorted(commit_times.items()):
        after = [t for t in times if t > kill_t]
        role = "joiner" if idx == 1 else ("survivor" if idx == 0 else f"g{idx}")
        out[role] = round(min(after) - kill_t, 3) if after else None
    return out


@contextmanager
def timed(name: str) -> Iterator[None]:
    """Always-on wall-time log for transfer-sized operations."""
    start = time.monotonic()
    try:
        yield
    finally:
        logger.info("%s took %.3fs", name, time.monotonic() - start)
