"""Deterministic interleaving control plane for the commit/quorum protocol.

The GIL only ever shows a handful of thread interleavings; the schedules
plane makes the rest reachable ON DEMAND.  Instrumented seams in the
coordination plane call :func:`point` ("I am at a schedule point named X");
when no :class:`Scheduler` is active — the shipped default — that call is a
few nanoseconds of module-global check and the production code path is
untouched.  When a scheduler IS active (only inside
``torchft_tpu.analysis.explore`` scenarios and their tests), every
*registered* thread parks at each point and a single controller decides,
deterministically, which parked thread runs next.  Foreign threads (manager
executors, watchdogs, heartbeats) pass through unscheduled: scenarios drive
the protocol from the threads they spawn, the same way the
threads-as-replicas integration tests do.

Exploration (:func:`explore`) enumerates schedules by DFS over recorded
decision points with iterative preemption bounding (the CHESS insight:
most concurrency bugs need very few preemptions), then a seeded-random
long tail.  Every run — passing or failing — has a one-line *replay
token* (``tpuft-sched:`` + base64 of the decision list) that
:func:`replay` turns back into the exact same interleaving.

Determinism caveat: a registered thread that blocks on a *real* lock held
by another registered thread cannot reach its next point; the controller
detects the stall (``stall_timeout``) and schedules someone else.  Those
fallback decisions depend on wall-clock time, so replay tokens are exact
for schedules whose points never straddle a real-lock wait and best-effort
otherwise — the explorer's scenarios keep their invariant checks
schedule-independent so a replayed token still reproduces the *violation*
even if the literal decision list re-records differently.

Env knobs (read by :func:`explore_defaults`, surfaced by doctor):
  TPUFT_EXPLORE_BUDGET       max schedules per scenario (default 64)
  TPUFT_EXPLORE_SEED         seed for the random long tail (default 0)
  TPUFT_EXPLORE_PREEMPTIONS  max preemption bound for the DFS legs (default 2)
  TPUFT_EXPLORE_RANDOM       random-schedule count after DFS (default 8)
"""

from __future__ import annotations

import base64
import json
import os
import random
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "point",
    "Scheduler",
    "ScheduleTrace",
    "ScheduleDeadlock",
    "ScheduleViolation",
    "ExploreResult",
    "run_schedule",
    "explore",
    "replay",
    "encode_token",
    "decode_token",
    "explore_defaults",
]

TOKEN_PREFIX = "tpuft-sched:"

# The active scheduler.  Reads are deliberately lock-free: `point` is on
# hot production paths (lockcheck acquire/release, pipeline push) and must
# cost one global load + one `is None` branch when exploration is off.
_active: Optional["Scheduler"] = None


def point(name: str, until: Optional[Callable[[], bool]] = None) -> None:
    """Schedule point.  No-op unless a Scheduler is active AND the calling
    thread is one the scheduler spawned.

    ``until`` makes the park *guarded*: the controller will not grant the
    thread until the predicate returns True (evaluated under the
    controller lock — keep it a cheap flag/Event check).  Scenarios use
    guards to encode protocol ordering contracts (e.g. "the quorum-change
    drain never overlaps new dispatches") without wall-clock waits or
    spin livelock; an unscheduled caller passes through and must enforce
    the same ordering with its own real synchronization."""
    sched = _active
    if sched is not None:
        sched._visit(name, until)


class ScheduleDeadlock(RuntimeError):
    """Every scheduled thread is blocked on a real lock and none arrives at
    a point within the deadlock timeout."""


@dataclass
class Decision:
    """One controller choice: which of the parked threads ran next."""

    options: Tuple[str, ...]  # sorted thread names that were runnable
    chosen: int  # index into options
    preempted: bool  # a different runnable thread was descheduled


@dataclass
class ScheduleTrace:
    decisions: List[Decision] = field(default_factory=list)
    points: List[Tuple[str, str]] = field(default_factory=list)  # (thread, point)

    @property
    def preemptions(self) -> int:
        return sum(1 for d in self.decisions if d.preempted)

    @property
    def token(self) -> str:
        return encode_token([d.chosen for d in self.decisions])


def encode_token(choices: Sequence[int]) -> str:
    raw = json.dumps(list(choices), separators=(",", ":")).encode()
    return TOKEN_PREFIX + base64.urlsafe_b64encode(raw).decode()


def decode_token(token: str) -> List[int]:
    if not token.startswith(TOKEN_PREFIX):
        raise ValueError(f"not a schedule token: {token!r}")
    raw = base64.urlsafe_b64decode(token[len(TOKEN_PREFIX):].encode())
    choices = json.loads(raw)
    if not isinstance(choices, list) or not all(
        isinstance(c, int) for c in choices
    ):
        raise ValueError(f"malformed schedule token payload: {token!r}")
    return choices


_NEW, _RUNNING, _WAITING, _BLOCKED, _DONE = range(5)


class _ThreadState:
    def __init__(self, name: str, index: int) -> None:
        self.name = name
        self.index = index
        self.status = _NEW
        self.granted = False
        self.at: Optional[str] = None
        self.guard: Optional[Callable[[], bool]] = None
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None

    def runnable(self) -> bool:
        if self.status != _WAITING:
            return False
        if self.guard is None:
            return True
        try:
            return bool(self.guard())
        except Exception:  # noqa: BLE001 — a broken guard must not wedge
            return True


class Scheduler:
    """Single-controller cooperative scheduler over spawned threads.

    Usage (normally via :func:`run_schedule`)::

        sched = Scheduler(choices=[0, 1, 0])
        sched.spawn("train", train_body)
        sched.spawn("quorum", drain_body)
        trace = sched.run()   # joins everything, re-raises thread errors
    """

    def __init__(
        self,
        choices: Optional[Sequence[int]] = None,
        rng: Optional[random.Random] = None,
        stall_timeout: float = 0.75,
        deadlock_timeout: float = 20.0,
    ) -> None:
        self._choices = list(choices or ())
        self._rng = rng
        self._stall_timeout = stall_timeout
        self._deadlock_timeout = deadlock_timeout
        self._cv = threading.Condition()
        self._tls = threading.local()
        self._threads: Dict[int, _ThreadState] = {}  # ident -> state
        self._states: List[_ThreadState] = []
        self._last: Optional[_ThreadState] = None
        self._decision_idx = 0
        self._draining = False  # True once run() finished: points pass through
        self.trace = ScheduleTrace()

    # -- thread side -------------------------------------------------------

    def spawn(self, name: str, fn: Callable[[], Any]) -> None:
        st = _ThreadState(name, len(self._states))
        self._states.append(st)

        def body() -> None:
            # Register under our own ident BEFORE the first point so the
            # start point is always scheduled (no start()-time race).
            with self._cv:
                self._threads[threading.get_ident()] = st
            try:
                self._visit(f"start:{name}")
                fn()
            except BaseException as e:  # noqa: BLE001 — reported by run()
                st.error = e
            finally:
                with self._cv:
                    # Unregister the ident: the OS reuses thread idents, so a
                    # foreign thread spawned after this one exits could
                    # otherwise be mistaken for it and parked forever.
                    self._threads.pop(threading.get_ident(), None)
                    st.status = _DONE
                    self._cv.notify_all()

        t = threading.Thread(target=body, name=f"sched-{name}", daemon=True)
        st.thread = t
        t.start()

    def _visit(
        self, name: str, until: Optional[Callable[[], bool]] = None
    ) -> None:
        if self._draining:
            return
        st = self._threads.get(threading.get_ident())
        if st is None:
            return  # foreign thread: pass through unscheduled
        # Reentrancy guard: instrumented primitives (lockcheck) fire points
        # from inside their own acquire/release hooks; a nested point while
        # this thread is already parked in scheduler machinery must pass
        # through, or it would re-enter self._cv and self-deadlock.
        if getattr(self._tls, "in_visit", False):
            return
        self._tls.in_visit = True
        try:
            self._visit_inner(st, name, until)
        finally:
            self._tls.in_visit = False

    def _visit_inner(
        self,
        st: "_ThreadState",
        name: str,
        until: Optional[Callable[[], bool]],
    ) -> None:
        with self._cv:
            if self._draining:
                return
            st.status = _WAITING
            st.at = name
            st.guard = until
            self._cv.notify_all()
            while not st.granted and not self._draining:
                self._cv.wait(0.5)
            st.granted = False
            st.guard = None
            st.status = _RUNNING
            self.trace.points.append((st.name, name))

    # -- controller side ---------------------------------------------------

    def _choose(self, runnable: List[_ThreadState]) -> _ThreadState:
        names = tuple(s.name for s in runnable)
        if self._decision_idx < len(self._choices):
            chosen = self._choices[self._decision_idx] % len(runnable)
        elif self._rng is not None:
            chosen = self._rng.randrange(len(runnable))
        elif self._last is not None and self._last in runnable:
            chosen = runnable.index(self._last)  # run-to-completion default
        else:
            chosen = 0
        self._decision_idx += 1
        preempted = (
            self._last is not None
            and self._last in runnable
            and runnable[chosen] is not self._last
        )
        self.trace.decisions.append(Decision(names, chosen, preempted))
        return runnable[chosen]

    def run(self) -> ScheduleTrace:
        """Drives the schedule to completion, joins every spawned thread,
        and re-raises the first thread error (annotated with the replay
        token)."""
        import time

        try:
            with self._cv:
                while True:
                    live = [s for s in self._states if s.status != _DONE]
                    if not live:
                        break
                    # Wait until nothing is RUNNING (or it stalls on a real
                    # lock), so decisions serialize the scheduled threads.
                    deadline = time.monotonic() + self._stall_timeout
                    while any(s.status == _RUNNING for s in live):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            for s in live:
                                if s.status == _RUNNING:
                                    s.status = _BLOCKED
                            break
                        self._cv.wait(remaining)
                        live = [s for s in self._states if s.status != _DONE]
                    live = [s for s in self._states if s.status != _DONE]
                    if not live:
                        break
                    # A BLOCKED thread that reached a point is WAITING
                    # again; a guarded park only becomes runnable once its
                    # predicate holds.
                    runnable = sorted(
                        (s for s in live if s.runnable()),
                        key=lambda s: s.name,
                    )
                    if not runnable:
                        # Everyone live is blocked on real locks or guarded
                        # parks; poll for a grantable arrival or full
                        # completion, bounded by the deadlock timeout.
                        # (Counting an already-_DONE thread as progress here
                        # would spin forever; polling, not a bare wait_for,
                        # because a guard can flip without a _cv notify.)
                        deadline = time.monotonic() + self._deadlock_timeout
                        ok = False
                        while time.monotonic() < deadline:
                            if any(
                                s.runnable() for s in self._states
                            ) or all(
                                s.status == _DONE for s in self._states
                            ):
                                ok = True
                                break
                            self._cv.wait(0.2)
                        if not ok:
                            raise ScheduleDeadlock(
                                "no scheduled thread became grantable within "
                                f"{self._deadlock_timeout}s; parked at: "
                                + ", ".join(
                                    f"{s.name}@{s.at}"
                                    for s in self._states
                                    if s.status in (_BLOCKED, _WAITING)
                                )
                            )
                        continue
                    chosen = self._choose(runnable)
                    chosen.granted = True
                    chosen.status = _RUNNING
                    self._last = chosen
                    self._cv.notify_all()
        finally:
            # Release everything still parked so join() can't hang.
            with self._cv:
                self._draining = True
                for s in self._states:
                    s.granted = True
                self._cv.notify_all()
            for s in self._states:
                if s.thread is not None:
                    s.thread.join(timeout=self._deadlock_timeout)
        for s in self._states:
            if s.error is not None:
                raise s.error
        return self.trace


# ---------------------------------------------------------------------------
# exploration
# ---------------------------------------------------------------------------


@dataclass
class ScheduleViolation:
    """A schedule under which the scenario's invariants broke."""

    token: str
    error: str
    error_type: str
    decisions: List[int]

    def format(self) -> str:
        return (
            f"schedule violation [{self.error_type}]: {self.error}\n"
            f"  replay: {self.token}"
        )


@dataclass
class ExploreResult:
    scenario: str
    schedules_run: int
    violation: Optional[ScheduleViolation]
    tokens_seen: int = 0

    @property
    def ok(self) -> bool:
        return self.violation is None


# A scenario is a callable taking the Scheduler (spawn threads on it, the
# caller runs them) and optionally returning a post-check callable that
# asserts invariants after all threads joined.  A ``cleanup`` attribute on
# the returned check, when present, always runs after the schedule —
# violation or not — so real-protocol scenarios can shut their manager
# down without leaking executor threads across hundreds of runs.
Scenario = Callable[[Scheduler], Optional[Callable[[], None]]]


def run_schedule(
    scenario: Scenario,
    choices: Optional[Sequence[int]] = None,
    rng: Optional[random.Random] = None,
    stall_timeout: float = 0.75,
) -> Tuple[ScheduleTrace, Optional[BaseException]]:
    """Runs ``scenario`` once under a fresh Scheduler.  Returns the trace
    and the first violation (thread error or post-check failure), if any.
    The scheduler is installed as the process-global active scheduler for
    the duration — scenarios must not run concurrently."""
    global _active
    sched = Scheduler(choices=choices, rng=rng, stall_timeout=stall_timeout)
    error: Optional[BaseException] = None
    check: Optional[Callable[[], None]] = None
    _active = sched
    try:
        check = scenario(sched)
        sched.run()
        if check is not None:
            check()
    except BaseException as e:  # noqa: BLE001 — classified by caller
        error = e
    finally:
        cleanup = getattr(check, "cleanup", None)
        if cleanup is not None:
            try:
                cleanup()
            except Exception:  # noqa: BLE001 — teardown must not mask the run
                pass
        _active = None
    return sched.trace, error


def _violation_from(
    trace: ScheduleTrace, error: BaseException
) -> ScheduleViolation:
    return ScheduleViolation(
        token=trace.token,
        error="".join(
            traceback.format_exception_only(type(error), error)
        ).strip(),
        error_type=type(error).__name__,
        decisions=[d.chosen for d in trace.decisions],
    )


def _prefix_preemptions(trace: ScheduleTrace, prefix_len: int, alt: int) -> int:
    """Preemption count of ``trace``'s decision prefix with the decision at
    ``prefix_len - 1`` replaced by option ``alt`` (an a-priori bound used to
    filter DFS branches before running them)."""
    count = 0
    last: Optional[str] = None
    for i, d in enumerate(trace.decisions[:prefix_len]):
        chosen = alt if i == prefix_len - 1 else d.chosen
        chosen %= len(d.options)
        name = d.options[chosen]
        if last is not None and last in d.options and name != last:
            count += 1
        last = name
    return count


def explore(
    scenario: Scenario,
    name: str = "scenario",
    budget: Optional[int] = None,
    preemption_bounds: Optional[Sequence[int]] = None,
    random_runs: Optional[int] = None,
    seed: Optional[int] = None,
    stall_timeout: float = 0.75,
    on_violation: Optional[Callable[[ScheduleViolation], None]] = None,
) -> ExploreResult:
    """Systematically explores ``scenario``'s interleavings.

    DFS over recorded decision points with iterative preemption bounding
    (bound 0 first, then 1, ...), then ``random_runs`` seeded-random
    schedules.  Stops at the first violation (returned with its replay
    token) or when ``budget`` schedules have run."""
    defaults = explore_defaults()
    budget = defaults["budget"] if budget is None else budget
    random_runs = defaults["random"] if random_runs is None else random_runs
    seed = defaults["seed"] if seed is None else seed
    if preemption_bounds is None:
        preemption_bounds = tuple(range(defaults["preemptions"] + 1))

    runs = 0
    # Prefix -> recorded trace.  Re-visiting a prefix at a higher
    # preemption bound reuses the cached trace for expansion instead of
    # re-running it (and instead of skipping it entirely, which would
    # leave every later bound with nothing to expand).
    cache: Dict[Tuple[int, ...], ScheduleTrace] = {}

    def one(choices=None, rng=None):
        nonlocal runs
        runs += 1
        trace, error = run_schedule(
            scenario, choices=choices, rng=rng, stall_timeout=stall_timeout
        )
        if error is not None:
            v = _violation_from(trace, error)
            if on_violation is not None:
                on_violation(v)
            return trace, v
        return trace, None

    for bound in preemption_bounds:
        frontier: List[List[int]] = [[]]
        queued = {()}
        while frontier and runs < budget:
            prefix = frontier.pop()
            key = tuple(prefix)
            trace = cache.get(key)
            if trace is None:
                trace, violation = one(choices=prefix)
                if violation is not None:
                    return ExploreResult(name, runs, violation, len(cache))
                cache[key] = trace
            # Expand alternatives at and beyond the prefix; the recorded
            # options at each decision tell us the branching factor.
            for i in range(len(prefix), len(trace.decisions)):
                d = trace.decisions[i]
                for alt in range(len(d.options)):
                    if alt == d.chosen % len(d.options):
                        continue
                    branch = tuple(
                        [x.chosen for x in trace.decisions[:i]] + [alt]
                    )
                    if _prefix_preemptions(trace, i + 1, alt) > bound:
                        continue
                    # Queue even cached branches: they won't re-run, but
                    # their recorded traces must be re-expanded under the
                    # current (higher) preemption bound.
                    if branch not in queued:
                        queued.add(branch)
                        frontier.append(list(branch))

    for j in range(random_runs):
        if runs >= budget:
            break
        trace, violation = one(rng=random.Random(seed + j))
        if violation is not None:
            return ExploreResult(name, runs, violation, len(cache))

    return ExploreResult(name, runs, None, len(cache))


def replay(
    scenario: Scenario, token: str, stall_timeout: float = 0.75
) -> Optional[ScheduleViolation]:
    """Re-runs ``scenario`` under the schedule encoded in ``token``.
    Returns the violation it reproduces, or None if the run passes."""
    choices = decode_token(token)
    trace, error = run_schedule(
        scenario, choices=choices, stall_timeout=stall_timeout
    )
    if error is None:
        return None
    return _violation_from(trace, error)


def explore_defaults() -> Dict[str, int]:
    """The TPUFT_EXPLORE_* env knobs with defaults (doctor probes these)."""

    def _int(env: str, default: int) -> int:
        raw = os.environ.get(env, "")
        try:
            return int(raw) if raw else default
        except ValueError:
            return default

    return {
        "budget": _int("TPUFT_EXPLORE_BUDGET", 64),
        "seed": _int("TPUFT_EXPLORE_SEED", 0),
        "preemptions": _int("TPUFT_EXPLORE_PREEMPTIONS", 2),
        "random": _int("TPUFT_EXPLORE_RANDOM", 8),
    }
