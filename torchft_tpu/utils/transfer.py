"""Device↔host transfer helpers."""

from __future__ import annotations

from typing import Iterable

__all__ = ["prefetch_to_host"]


def prefetch_to_host(leaves: Iterable) -> None:
    """Launches a non-blocking device→host copy for every jax array.

    Call before a sequence of per-leaf ``np.asarray`` drains: the copies
    then progress concurrently (and overlap whatever the caller does next)
    instead of serializing one device round trip per leaf — which dominates
    on high-latency device links. Non-array leaves (already-host numpy,
    scalars) are skipped; jax arrays are matched by the
    ``copy_to_host_async`` attribute so sharded/committed array flavors all
    qualify.
    """
    for leaf in leaves:
        if hasattr(leaf, "copy_to_host_async"):
            leaf.copy_to_host_async()
