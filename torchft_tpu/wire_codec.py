"""Quantized wire plane: one codec registry for every bulk byte path.

WIRE_CONVERGENCE.json retired the quality risk of the 8/4-bit formats
(fp8/int4 outer syncs are quality-neutral vs fp32, same seed), but until
this module only the DDP/DiLoCo gradient wires spent that win. Here the
same host codecs (:mod:`torchft_tpu.ops.quantization` — the reference's
Triton-kernel lineage, torchft/quantization.py) become a *wire format*
every bulk path consults:

- **heal streams** (``$TPUFT_HEAL_CODEC``): the donor stages encoded
  chunks; CRC + digest are computed over the ENCODED bytes, so tamper
  detection, delta rejoin's (crc, size) matching, striped-heal
  reassignment, and serve-child isolation all work unchanged on the
  compressed payload. Decode runs joiner-side AFTER CRC verification.
- **serving fan-out** (``$TPUFT_SERVING_CODEC``): the publisher stages
  encoded versions; relays cache and fan out the encoded bytes verbatim
  (they are byte-level), readers decode after verify-then-swap.
- **ZeRO shard plane** (``$TPUFT_ZERO_CODEC``): the flat f32 plane
  encodes on the reduce-scatter and allgather wires
  (:class:`torchft_tpu.zero.ZeroOptimizer`); masters stay f32 and
  bitwise replica identity survives BY CONSTRUCTION because every
  replica dequantizes the same encoded allgather payload with one shared
  dispatch.

All three default to ``fp32`` — a passthrough that keeps every byte,
/meta field, and wire payload bit-for-bit identical to the pre-codec
format (pinned by tests). A codec-less (format-2) peer therefore
interoperates by default; with a codec enabled the staged ``/meta``
bumps to format 3, so an old joiner REFUSES the stage cleanly instead of
ever misdecoding (see ``docs/resilience.md``).

Wire format
-----------

Encoding is a *leaf transform*: each eligible float array leaf is
replaced by a marker dict ::

    {CODEC_KEY: "int8", "shape": (..), "dtype": "float32",
     "payload": uint8/int8/fp8 (n_blocks, cols), "scales": f32 (n_blocks,)}

The marker rides INSIDE the chunk bytes (covered by the per-chunk CRC
and the digest binding), so decode is structure-driven and
self-verifying: a wrong or lying codec tag — payload dtype, block
geometry, or scale shape that does not match the claimed codec — raises
:class:`WireCodecError` and the state is never adopted (heal callers
funnel it into ``Manager.report_error``; serving readers count it as a
failed poll and keep their held version). Integer leaves, tiny leaves
(< :data:`MIN_ENCODE_ELEMS` elements), and non-fully-addressable
multi-host arrays pass through unencoded.

The per-chunk ``codec`` field in ``/meta`` (``chunk_codecs``) and the
serving descriptor is bound into the checkpoint digest, so a tampered
tag fails the digest check before any payload transfer.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu import metrics
from torchft_tpu.ops import quantization as q

__all__ = [
    "CODECS",
    "CODEC_KEY",
    "ENV_HEAL_CODEC",
    "ENV_SERVING_CODEC",
    "ENV_ZERO_CODEC",
    "MIN_ENCODE_ELEMS",
    "WireCodecError",
    "heal_codec",
    "serving_codec",
    "zero_codec",
    "resolve_codec",
    "is_encoded_leaf",
    "encode_state",
    "decode_state",
    "encoded_ratio",
]

ENV_HEAL_CODEC = "TPUFT_HEAL_CODEC"
ENV_SERVING_CODEC = "TPUFT_SERVING_CODEC"
ENV_ZERO_CODEC = "TPUFT_ZERO_CODEC"

# "fp32" is the identity codec: no transform, no /meta field, format 2 —
# bit-for-bit the pre-codec wire. The others reuse the block codecs in
# ops/quantization.py (BLOCK-element blocks, one f32 scale per block).
CODECS = ("fp32", "fp8", "int8", "int4")

# Sentinel key marking an encoded leaf's marker dict. Rides the chunk
# header (pickled non-array leaf), so it is covered by the chunk CRC.
CODEC_KEY = "__tpuft_wire_codec__"

# Leaves below this element count pass through unencoded: the per-block
# scale + padding overhead wipes out the byte win on tiny leaves, and
# scalars (step counters) must stay exact.
MIN_ENCODE_ELEMS = 1024

# Numeric code per codec for the `tpuft_codec_wire` gauge (fleet_status's
# WIRE column decodes it back).
CODEC_GAUGE_CODES = {"fp32": 0, "fp8": 1, "int8": 2, "int4": 3}
GAUGE_CODE_CODECS = {v: k for k, v in CODEC_GAUGE_CODES.items()}


class WireCodecError(RuntimeError):
    """An encoded leaf failed validation (wrong/lying codec tag, payload
    geometry, or dtype): the bytes verified their CRC but do not decode
    as the codec they claim — corrupt-by-construction, never adopted."""


def _env_codec(env: str) -> str:
    raw = os.environ.get(env)
    if raw is None or raw.strip() == "":
        return "fp32"
    name = raw.strip().lower()
    if name not in CODECS:
        raise ValueError(
            f"{env}={raw!r} is not one of {sorted(CODECS)}"
        )
    return name


def heal_codec() -> str:
    """Heal-stream wire codec (``$TPUFT_HEAL_CODEC``, default fp32)."""
    return _env_codec(ENV_HEAL_CODEC)


def serving_codec() -> str:
    """Serving fan-out wire codec (``$TPUFT_SERVING_CODEC``, default fp32)."""
    return _env_codec(ENV_SERVING_CODEC)


def zero_codec() -> str:
    """ZeRO shard-plane wire codec (``$TPUFT_ZERO_CODEC``, default fp32)."""
    return _env_codec(ENV_ZERO_CODEC)


def resolve_codec(codec: Optional[str]) -> str:
    """Validates an explicit codec name; None means fp32 passthrough."""
    if codec is None:
        return "fp32"
    if codec not in CODECS:
        raise ValueError(f"codec={codec!r} is not one of {sorted(CODECS)}")
    return codec


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _is_float_dtype(dtype: np.dtype) -> bool:
    if dtype.kind == "f":
        return True
    # ml_dtypes customs (bfloat16 & friends) register as void-kind; the
    # quantizer upcasts them through float32 exactly like the DDP wire.
    try:
        import ml_dtypes

        # Deliberately NOT the fp8 wire dtype itself: an fp8 array is
        # either already a wire payload (never double-encode) or exotic
        # enough that passthrough is the safe default.
        return dtype == np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover
        return False


def _eligible(leaf: Any) -> bool:
    """Encodable: a host/jax float array of at least MIN_ENCODE_ELEMS
    elements, fully addressable (multi-host shard captures pass through —
    they serialize per-shard and re-assemble receiver-side)."""
    if isinstance(leaf, dict) and CODEC_KEY in leaf:
        return False  # already encoded — never double-encode
    if not (hasattr(leaf, "dtype") and hasattr(leaf, "shape")):
        return False
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        return False
    try:
        dtype = np.dtype(leaf.dtype)
    except TypeError:
        return False
    if not _is_float_dtype(dtype):
        return False
    size = 1
    for dim in leaf.shape:
        size *= int(dim)
    return size >= MIN_ENCODE_ELEMS


def is_encoded_leaf(node: Any) -> bool:
    """True for a marker dict produced by :func:`encode_state` (the key
    survives the wire even when a skipped part nulled the values)."""
    return isinstance(node, dict) and CODEC_KEY in node


def _encode_leaf(leaf: Any, codec: str) -> Dict[str, Any]:
    arr = np.asarray(leaf)
    payload, scales = q.quantize_blocks(arr, wire=codec)
    return {
        CODEC_KEY: codec,
        "shape": tuple(int(d) for d in arr.shape),
        "dtype": np.dtype(arr.dtype).name,
        "payload": payload,
        "scales": scales,
    }


def _decode_leaf(marker: Dict[str, Any]) -> Any:
    codec = marker.get(CODEC_KEY)
    payload = marker.get("payload")
    scales = marker.get("scales")
    if codec is None or payload is None or scales is None:
        # A skipped heal part substituted None for this chunk's leaves;
        # the part owner reconstructs the state through its own plane.
        return None
    if codec not in CODECS or codec == "fp32":
        raise WireCodecError(f"unknown wire codec tag {codec!r} in payload")
    shape = marker.get("shape")
    dtype_name = marker.get("dtype")
    if shape is None or dtype_name is None:
        raise WireCodecError(f"encoded {codec} leaf is missing shape/dtype")
    payload = np.asarray(payload)
    scales = np.asarray(scales)
    expect_dtype = q._WIRE_NP_DTYPES[codec]
    cols = q.payload_cols(codec)
    size = 1
    for dim in shape:
        size *= int(dim)
    n_blocks = -(-max(size, 1) // q.BLOCK)
    # The tag is self-verifying: payload dtype AND block geometry must
    # match the claimed codec exactly, or these bytes were produced by a
    # different codec than the tag says (a lying tag / cross-codec mixup)
    # and decoding them would fabricate state.
    if np.dtype(payload.dtype) != expect_dtype:
        raise WireCodecError(
            f"lying codec tag: payload dtype {payload.dtype} does not match "
            f"claimed codec {codec!r} (expected {expect_dtype})"
        )
    if payload.shape != (n_blocks, cols):
        raise WireCodecError(
            f"lying codec tag: {codec} payload shape {payload.shape} does "
            f"not match the leaf geometry (expected {(n_blocks, cols)})"
        )
    if scales.shape != (n_blocks,) or np.dtype(scales.dtype) != np.float32:
        raise WireCodecError(
            f"corrupt {codec} scales: shape {scales.shape} dtype "
            f"{scales.dtype} (expected ({n_blocks},) float32)"
        )
    return q.dequantize_blocks(
        payload, scales, tuple(shape), _resolve_dtype(dtype_name)
    )


def encode_state(
    state: Any, codec: Optional[str], wire: str = "heal"
) -> Tuple[Any, Dict[str, int]]:
    """Encodes every eligible float leaf of ``state`` with ``codec``;
    returns ``(encoded_state, stats)`` where stats carries the exact
    pre/post byte accounting (also emitted as ``tpuft_codec_*``
    counters labeled ``wire=``/``codec=``). ``codec`` None/"fp32" is the
    identity: the INPUT object is returned untouched, so the default
    path stays bit-for-bit (and allocation-free)."""
    import jax

    codec = resolve_codec(codec)
    stats = {"encoded_leaves": 0, "pre_bytes": 0, "post_bytes": 0}
    if codec == "fp32":
        return state, stats
    t0 = time.perf_counter()

    def enc(leaf: Any) -> Any:
        if not _eligible(leaf):
            return leaf
        marker = _encode_leaf(leaf, codec)
        stats["encoded_leaves"] += 1
        stats["pre_bytes"] += int(np.dtype(leaf.dtype).itemsize) * int(
            np.prod(marker["shape"], dtype=np.int64)
        )
        stats["post_bytes"] += int(
            marker["payload"].nbytes + marker["scales"].nbytes
        )
        return marker

    encoded = jax.tree_util.tree_map(enc, state)
    dt = time.perf_counter() - t0
    metrics.observe("tpuft_codec_encode_seconds", dt, wire=wire)
    if stats["encoded_leaves"]:
        metrics.inc(
            "tpuft_codec_bytes_pre_total", stats["pre_bytes"],
            wire=wire, codec=codec,
        )
        metrics.inc(
            "tpuft_codec_bytes_post_total", stats["post_bytes"],
            wire=wire, codec=codec,
        )
    metrics.set_gauge(
        "tpuft_codec_wire", CODEC_GAUGE_CODES[codec], wire=wire
    )
    return encoded, stats


def decode_state(state: Any, wire: str = "heal") -> Any:
    """Inverse of :func:`encode_state`: replaces every marker dict with
    its dequantized array (or None when a skipped part nulled it).
    Structure-driven — an unencoded tree passes through untouched — and
    self-verifying: any marker whose payload does not match its claimed
    codec raises :class:`WireCodecError` (counted in
    ``tpuft_codec_decode_failures_total``), so a lying tag can never
    become adopted state."""
    import jax

    t0 = time.perf_counter()
    found = [0]

    def dec(node: Any) -> Any:
        if is_encoded_leaf(node):
            found[0] += 1
            return _decode_leaf(node)
        return node

    try:
        decoded = jax.tree_util.tree_map(
            dec, state, is_leaf=lambda x: is_encoded_leaf(x)
        )
    except WireCodecError:
        metrics.inc("tpuft_codec_decode_failures_total", wire=wire)
        raise
    if found[0]:
        metrics.observe(
            "tpuft_codec_decode_seconds", time.perf_counter() - t0, wire=wire
        )
    return decoded


def encoded_ratio(stats: Dict[str, int]) -> Optional[float]:
    """post/pre byte ratio of one encode pass (None when nothing encoded)."""
    if not stats.get("pre_bytes"):
        return None
    return stats["post_bytes"] / stats["pre_bytes"]


def chunk_codecs_for(num_chunks: int, codec: Optional[str]) -> Optional[List[str]]:
    """The per-chunk codec tag list for a stage: None for the fp32
    default (the /meta stays format 2, bit-for-bit), else one tag per
    chunk (decode is structure-driven; the tag is the negotiation +
    digest-binding surface)."""
    codec = resolve_codec(codec)
    if codec == "fp32":
        return None
    return [codec] * num_chunks
