"""Async work handles for collectives.

``Work`` plays the role of ``torch.distributed.Work`` in the reference;
``_DummyWork`` is the universal "skip this collective" value
(/root/reference/torchft/work.py:9-20) the manager substitutes when a replica
is not participating or the group has errored.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Callable, Optional

__all__ = ["Work", "_DummyWork"]


class Work:
    """Handle for an asynchronous collective; resolves to the op's result."""

    def __init__(self, future: "Future[Any]") -> None:
        self._future = future

    @classmethod
    def completed(cls, value: Any) -> "Work":
        fut: Future = Future()
        fut.set_result(value)
        return cls(fut)

    @classmethod
    def failed(cls, error: BaseException) -> "Work":
        fut: Future = Future()
        fut.set_exception(error)
        return cls(fut)

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Blocks until done; returns the result or raises the op's error."""
        return self._future.result(timeout)

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._future.result(timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        return self._future.exception(timeout)

    def add_done_callback(self, fn: Callable[["Future[Any]"], None]) -> None:
        self._future.add_done_callback(fn)

    def then(self, fn: Callable[[Any], Any]) -> "Work":
        """Chains a transform over the result; errors propagate."""
        out: Future = Future()

        def callback(fut: "Future[Any]") -> None:
            try:
                out.set_result(fn(fut.result()))
            except BaseException as e:  # noqa: BLE001
                out.set_exception(e)

        self._future.add_done_callback(callback)
        return Work(out)

    @classmethod
    def gather(cls, works: "list[Work]") -> "Work":
        """Combines several works into one resolving to the list of their
        results (in input order). The first failure wins and propagates."""
        out: Future = Future()
        results: list = [None] * len(works)
        state = {"remaining": len(works), "failed": False}
        lock = threading.Lock()

        if not works:
            out.set_result([])
            return cls(out)

        def make_callback(index: int) -> Callable[["Future[Any]"], None]:
            def callback(fut: "Future[Any]") -> None:
                err = fut.exception()
                with lock:
                    if state["failed"]:
                        return
                    if err is not None:
                        state["failed"] = True
                        out.set_exception(err)
                        return
                    results[index] = fut.result()
                    state["remaining"] -= 1
                    finished = state["remaining"] == 0
                if finished:
                    out.set_result(list(results))

            return callback

        for index, work in enumerate(works):
            work._future.add_done_callback(make_callback(index))
        return cls(out)

    def with_error_handler(
        self, handler: Callable[[Exception], None], fallback: Any
    ) -> "Work":
        """On failure: reports the error to ``handler`` and resolves to
        ``fallback`` instead (the error-swallowing contract)."""
        out: Future = Future()

        def callback(fut: "Future[Any]") -> None:
            err = fut.exception()
            if err is None:
                out.set_result(fut.result())
            else:
                try:
                    handler(err if isinstance(err, Exception) else RuntimeError(str(err)))
                finally:
                    out.set_result(fallback)

        self._future.add_done_callback(callback)
        return Work(out)


class _DummyWork(Work):
    """Already-completed no-op work holding a fixed result."""

    def __init__(self, result: Any) -> None:
        fut: Future = Future()
        fut.set_result(result)
        super().__init__(fut)
