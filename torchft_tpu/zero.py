"""ZeRO plane: FT-aware cross-replica sharding of the optimizer update.

Every replica in plain FT-DDP redundantly holds full params + full
optimizer state and applies the full update. This module shards the
*update* across the replica axis ("Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training", PAPERS.md) without ever putting
that axis in the jax Mesh — membership changes must never recompile XLA
programs (the architecture invariant R5 enforces statically). Per step:

1. gradients pack into ONE flat f32 buffer (one jitted dispatch, one d2h
   fetch) and reduce across replica groups over the FT collectives —
   ``pg.reduce_scatter`` when the shard layout permits, allreduce+slice
   otherwise (bitwise-identical bytes either way on the TCP backend);
2. each live replica runs the jitted optax update (ONE
   ``make_jit_shard_update`` dispatch) on only the shards it owns — the
   owner holds the f32 *master* range plus that range's optax state;
3. the updated master ranges allgather and every replica unpacks the same
   flat buffer into model-dtype params — **bitwise identity across
   replica groups holds by construction** (each range's bytes are
   computed exactly once, by its owner, and broadcast).

Gradient math stays world-size independent: SUM + divide by the live
participant count; non-participants contribute zeros and own nothing.
With N participants each replica persists ~1/N of (masters + moments),
and the heal plane ships ~1/N (or, with the default skip-all heal
policy, none) of the optimizer bytes a full checkpoint would.

**Elasticity** is the hard part: shard ownership is a pure function of
(number of shards, live cohort size, step) — ``shard_assignment`` —
recomputed whenever the quorum's shape changes. Re-balance is lazy and
wire-lockstep: at the first step of a new assignment every PG member
exchanges tiny shard *manifests* (ids + the committed step each shard
state corresponds to), derives the same deterministic transfer plan, and
moves **only the shard states whose ownership changed** point-to-point.
A shard whose holder died is reconstructed deterministically: its master
range re-packs from the (replicated, committed) params — exact for f32
models — and its moments restart from ``tx.init`` (counted in
``tpuft_zero_shard_reinits_total``; the documented bounded-staleness
envelope). Stale holders (a joiner that kept shards across a death) are
fenced by the manifest step tag and never chosen as a source.

Heals are shard-addressable end to end: the optimizer registers each
shard's state under a ``heal_part:zero_shard_<s>`` key, the checkpoint
transport stages each part as its own CRC'd chunk, and the joiner skips
the parts it can re-balance from survivors (``TPUFT_ZERO_HEAL_SHARDS``;
the skipped bytes land in ``tpuft_zero_heal_bytes_saved_total``).

**Quantized shard wire** (``$TPUFT_ZERO_CODEC``, default fp32): the flat
f32 plane encodes to fp8/int8/int4 on both bulk legs — the grad reduce
rides the fused dequant-reduce-requant allreduce
(:func:`torchft_tpu.parallel.collectives.allreduce_quantized`) and the
master allgather ships packed ``[tag||scales||payload]`` ranges that
EVERY replica (owners included) dequantizes identically, so bitwise
replica identity survives by construction while the replica-axis bytes
drop ~4x (8-bit) / ~8x (int4). Masters stay f32 on their owners; the
env must agree fleet-wide (the wire tag turns disagreement into a hard
error). See docs/zero.md.

Composes with all three commit orderings (strict / overlapped /
pipelined — rollback snapshots are whole :class:`ZeroState` objects,
rebound never mutated), with DiLoCo/LocalSGD manager registration
(distinct state-dict keys), and with the lone-replica identity skip
(N=1 owns every shard and touches no wire). See docs/zero.md.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from torchft_tpu import metrics, tracing, wire_codec
from torchft_tpu.checkpointing.transport import HEAL_PART_PREFIX
from torchft_tpu.ops import quantization as q
from torchft_tpu.parallel.collectives import allreduce_quantized
from torchft_tpu.manager import Manager
from torchft_tpu.optim import (
    Optimizer,
    _as_device_tree,
    _replica_labels,
    _sync_device,
    _trace_of,
    make_jit_shard_update,
)
from torchft_tpu.parallel.process_group import ReduceOp

logger = logging.getLogger(__name__)

__all__ = [
    "ShardSpec",
    "ZeroState",
    "ZeroOptimizer",
    "shard_assignment",
    "shard_part_name",
    "plan_shard_moves",
]

ENV_ZERO = "TPUFT_ZERO"
ENV_ZERO_SHARDS = "TPUFT_ZERO_SHARDS"
ENV_ZERO_REBALANCE = "TPUFT_ZERO_REBALANCE"
ENV_ZERO_HEAL_SHARDS = "TPUFT_ZERO_HEAL_SHARDS"

DEFAULT_NUM_SHARDS = 8


def shard_part_name(shard: int) -> str:
    """The heal-part key for one shard's state (the checkpoint transport
    stages each such part as its own independently-fetchable chunk)."""
    return f"{HEAL_PART_PREFIX}zero_shard_{shard}"


def shard_assignment(
    num_shards: int,
    num_participants: int,
    step: int = 0,
    policy: Optional[str] = None,
) -> np.ndarray:
    """Owner (participant rank) per shard: a pure function of the sorted
    quorum cohort's size and the step — every replica computes the same
    array with NO communication (the unit tests pin determinism).

    Policies (``$TPUFT_ZERO_REBALANCE``):

    - ``block`` (default): contiguous blocks of shards per rank
      (``np.array_split`` semantics) — block layouts make the
      ``pg.reduce_scatter`` fast path possible and minimize the number of
      ownership moves when the cohort shrinks or grows by one.
    - ``strided``: ``owner[s] = s % N`` — spreads hot shards when shard
      sizes are skewed.

    ``step`` is part of the signature so a step-keyed rotation policy
    stays a pure function of (cohort, step); the shipped policies are
    deliberately step-invariant (rotation would churn shard state every
    step for no FT benefit).
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    n = max(1, int(num_participants))
    policy = policy or os.environ.get(ENV_ZERO_REBALANCE, "block")
    if policy == "block":
        owners = np.empty(num_shards, dtype=np.int64)
        for rank, block in enumerate(
            np.array_split(np.arange(num_shards), min(n, num_shards))
        ):
            owners[block] = rank
        return owners
    if policy == "strided":
        return np.arange(num_shards, dtype=np.int64) % n
    raise ValueError(
        f"{ENV_ZERO_REBALANCE} must be 'block' or 'strided', got {policy!r}"
    )


@dataclass(frozen=True)
class _LeafMeta:
    shape: Tuple[int, ...]
    dtype: Any
    size: int
    offset: int


class ShardSpec:
    """The flat-buffer shard geometry over one params pytree.

    Leaves concatenate (flatten order — deterministic across replicas for
    identical models, the frozen-bucket invariant) into one conceptual f32
    buffer of ``total`` elements, zero-padded to ``num_shards`` equal
    ranges of ``shard_len`` elements each. Equal ranges keep the
    re-balance wire format and the jitted shard update shape-stable no
    matter which shards a replica owns. The replica axis never appears in
    any jax Mesh: sharding is plain python range bookkeeping + host
    collectives, so membership changes recompile nothing.
    """

    def __init__(self, params: Any, num_shards: int) -> None:
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if not leaves:
            raise ValueError("ShardSpec needs at least one parameter leaf")
        self.treedef = treedef
        metas: List[_LeafMeta] = []
        offset = 0
        for leaf in leaves:
            if not hasattr(leaf, "shape"):
                raise ValueError(
                    "ZeRO shards array leaves only; found a non-array param "
                    f"leaf of type {type(leaf).__name__}"
                )
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            metas.append(
                _LeafMeta(tuple(leaf.shape), np.dtype(leaf.dtype), size, offset)
            )
            offset += size
        self.leaf_metas = metas
        self.total = offset
        self.num_shards = int(num_shards)
        self.shard_len = -(-self.total // self.num_shards)  # ceil
        self.padded = self.shard_len * self.num_shards

        import jax.numpy as jnp

        def _pack(tree: Any) -> Any:
            flat_leaves = jax.tree_util.tree_leaves(tree)
            flat = jnp.concatenate(
                [leaf.astype(jnp.float32).reshape(-1) for leaf in flat_leaves]
            )
            return jnp.pad(flat, (0, self.padded - self.total))

        def _unpack(flat: Any) -> Any:
            outs = []
            for meta in metas:
                chunk = jax.lax.dynamic_slice_in_dim(flat, meta.offset, meta.size)
                outs.append(chunk.reshape(meta.shape).astype(meta.dtype))
            return jax.tree_util.tree_unflatten(treedef, outs)

        self.pack = jax.jit(_pack)
        self.unpack = jax.jit(_unpack)

    def shard_range(self, shard: int) -> Tuple[int, int]:
        start = shard * self.shard_len
        return start, start + self.shard_len

    def shard_view(self, flat: np.ndarray, shard: int) -> np.ndarray:
        start, stop = self.shard_range(shard)
        return flat[start:stop]

    def describe(self) -> Dict[str, Any]:
        return {
            "num_shards": self.num_shards,
            "total": self.total,
            "shard_len": self.shard_len,
            "num_leaves": len(self.leaf_metas),
        }


@dataclass(frozen=True)
class _ShardState:
    """One shard's persisted optimizer state: the f32 master range plus
    that range's optax state, tagged with the committed step it
    corresponds to (the re-balance manifest's freshness fence)."""

    step: int
    master: Any  # (shard_len,) f32
    opt: Any  # optax state pytree for this range


@dataclass(frozen=True)
class ZeroState:
    """The sharded optimizer state one replica persists. Immutable —
    updates build a new instance, so the commit pipeline's rollback
    snapshots and the donor's checkpoint captures are plain reference
    rebinds (never torn, never mutated in place)."""

    spec: ShardSpec
    held: Dict[int, _ShardState] = field(default_factory=dict)
    step: int = 0
    # The (quorum_id, pg_rank, pg_world, participating_rank,
    # num_participants) this state's ownership was balanced for; None
    # forces a re-balance at the next step (fresh construction, heal).
    balance_key: Optional[Tuple] = None
    ever_balanced: bool = False
    # Proven at the last manifest exchange: participant rank r sits at PG
    # rank r for every participant. This is the evidence gate for the
    # pg.reduce_scatter fast path — chunk-by-PG-rank only routes ranges
    # to their owners when the two rank spaces coincide, and assuming it
    # without proof would silently corrupt the update on a permuted
    # cohort.
    ranks_identical: bool = False

    def owned_bytes(self) -> int:
        total = 0
        for state in self.held.values():
            total += int(np.asarray(state.master).nbytes)
            for leaf in jax.tree_util.tree_leaves(state.opt):
                total += int(np.asarray(leaf).nbytes)
        return total


def plan_shard_moves(
    manifests: Sequence[Tuple[int, int, Sequence[Tuple[int, int]]]],
    owners: np.ndarray,
    participant_pg_ranks: Dict[int, int],
    current_step: int,
) -> Tuple[List[Tuple[int, int, int]], List[int]]:
    """The deterministic re-balance transfer plan every rank derives from
    the same manifest exchange (pure function — no further negotiation).

    ``manifests``: per PG member ``(pg_rank, state_step, [(shard_id,
    shard_step), ...])``. ``owners``: participant rank per shard
    (:func:`shard_assignment`). ``participant_pg_ranks``: participant
    rank -> PG rank (derived from the same manifests by the caller).

    Returns ``(moves, lost)``: ``moves`` is ``[(shard, src_pg_rank,
    dst_pg_rank), ...]`` sorted by shard id — ONLY shards whose
    current-step holder is not their new owner; ``lost`` is the shard ids
    no live member holds at ``current_step`` (reconstructed by their new
    owner, counted as reinits once the plane has balanced before).
    Holders whose shard tag is behind ``current_step`` are stale (a
    rejoined replica that kept state across a death) and are never chosen
    as a source.
    """
    holders: Dict[int, List[int]] = {}
    for pg_rank, _state_step, entries in manifests:
        for shard_id, shard_step in entries:
            if shard_step == current_step:
                holders.setdefault(int(shard_id), []).append(int(pg_rank))
    moves: List[Tuple[int, int, int]] = []
    lost: List[int] = []
    for shard in range(len(owners)):
        owner_pg = participant_pg_ranks.get(int(owners[shard]))
        if owner_pg is None:
            # The owner is not a live PG member this round (can only
            # happen transiently while the quorum settles); nobody
            # fetches the shard — its holder keeps it for the next plan.
            continue
        ranked = sorted(holders.get(shard, []))
        if not ranked:
            lost.append(shard)
            continue
        src = ranked[0]
        if src != owner_pg:
            moves.append((shard, src, owner_pg))
    return moves, lost


class ZeroOptimizer(Optimizer):
    """:class:`~torchft_tpu.optim.Optimizer` with the update sharded
    across the replica axis (see module docstring for the protocol).

    API differences from the base class, both deliberate:

    - :meth:`step` takes the **local** (unaveraged) gradient pytree — the
      cross-replica reduction IS the reduce-scatter half of the sharded
      update, so averaging first (``ft_allreduce_gradients``) would pay
      the wire twice. ``make_step_fn`` handles this transparently.
    - ``opt_state`` is a :class:`ZeroState` (opaque to the commit
      pipeline's snapshot/rollback machinery, which only rebinds refs).

    ``num_shards`` is fixed for the life of the job (and must match
    across replicas — it keys the shard-addressable heal format); choose
    a value divisible by the cohort sizes you expect so the
    ``pg.reduce_scatter`` fast path engages (``$TPUFT_ZERO_SHARDS``,
    default 8, covers 1/2/4/8). The sharded wire quantizes through
    ``$TPUFT_ZERO_CODEC`` (fp8/int8/int4; fleet-wide agreement like
    ``TPUFT_WIRE_DTYPE``) — NOT through the per-call ``should_quantize``
    flag, which remains a no-op warning here: the codec is a wire
    format, not a step flag, because every replica must decode the same
    allgather bytes for bitwise identity to survive.
    """

    def __init__(
        self,
        manager: Manager,
        tx: Any,
        params: Any,
        num_shards: Optional[int] = None,
        register_key: str = "zero",
    ) -> None:
        if num_shards is None:
            num_shards = int(
                os.environ.get(ENV_ZERO_SHARDS, str(DEFAULT_NUM_SHARDS))
            )
        self._num_shards = int(num_shards)
        self._spec: Optional[ShardSpec] = None  # built inside _init_state
        super().__init__(manager, tx, params, register_key=register_key)
        self._jit_shard_update = make_jit_shard_update(tx)
        import jax.numpy as jnp

        # Shared template for every shard's optax state: equal ranges mean
        # ONE structure (treedef + leaf shapes) describes all shards — the
        # re-balance recv templates and the heal payloads lean on this.
        self._opt_template = tx.init(
            jnp.zeros((self._spec.shard_len,), jnp.float32)
        )
        self._opt_treedef = jax.tree_util.tree_structure(self._opt_template)
        self._opt_leaf_templates = [
            np.zeros(np.shape(leaf), dtype=np.asarray(leaf).dtype)
            for leaf in jax.tree_util.tree_leaves(self._opt_template)
        ]
        heal_policy = os.environ.get(ENV_ZERO_HEAL_SHARDS, "skip")
        if heal_policy not in ("skip", "fetch"):
            raise ValueError(
                f"{ENV_ZERO_HEAL_SHARDS} must be 'skip' or 'fetch', "
                f"got {heal_policy!r}"
            )
        if heal_policy == "skip":
            # A joiner re-balances its shards from survivors over the PG,
            # so the heal stream need not carry the donor's shard states
            # at all: skip those parts (the transport pins the saved bytes
            # in tpuft_zero_heal_bytes_saved_total).
            manager.register_heal_parts_filter(
                lambda: {shard_part_name(s) for s in range(self._num_shards)}
            )
        metrics.set_gauge(
            "tpuft_zero_num_shards", self._num_shards, **_replica_labels(manager)
        )

    # ------------------------------------------------------------------
    # state construction / registration
    # ------------------------------------------------------------------

    def _init_state(self, tx: Any, params: Any) -> ZeroState:
        self._spec = ShardSpec(params, self._num_shards)
        # Held shards start EMPTY: ownership is unknown until the first
        # quorum, and bootstrapping an owned shard (master re-packed from
        # the replicated params, moments from tx.init) is deterministic —
        # identical on every replica at step 0 by the init_sync contract.
        return ZeroState(spec=self._spec, held={}, step=0, balance_key=None)

    def _state_dict(self) -> Any:
        state: ZeroState = self.opt_state
        shards: Dict[str, Any] = {}
        for s in range(self._num_shards):
            held = state.held.get(s)
            if held is None:
                shards[shard_part_name(s)] = None
            else:
                shards[shard_part_name(s)] = {
                    "step": held.step,
                    "master": held.master,
                    "opt": held.opt,
                }
        return {
            "params": self.params,
            "zero": {"num_shards": self._num_shards, "step": state.step},
            "shards": shards,
        }

    # tpuft: allow(lock-discipline): heal apply — the registered load fns run under the state-dict writer taken by Manager._apply_pending_state_dict
    def _load_state_dict(self, state: Any) -> None:
        import jax.numpy as jnp

        meta = state["zero"]
        if int(meta["num_shards"]) != self._num_shards:
            raise ValueError(
                f"donor runs {meta['num_shards']} ZeRO shards, this replica "
                f"runs {self._num_shards}: num_shards must match fleet-wide "
                f"(${ENV_ZERO_SHARDS})"
            )
        self.params = _as_device_tree(state["params"], like=self.params)
        held: Dict[int, _ShardState] = {}
        for s in range(self._num_shards):
            payload = state["shards"].get(shard_part_name(s))
            if payload is None or payload.get("master") is None:
                # Not held by the donor, or a skip_parts heal substituted
                # None for the part's leaves — either way the shard state
                # arrives through the re-balance exchange instead.
                continue
            held[s] = _ShardState(
                step=int(payload["step"]),
                master=jnp.asarray(np.asarray(payload["master"])),
                opt=jax.tree_util.tree_map(
                    lambda x: jnp.asarray(np.asarray(x)), payload["opt"]
                ),
            )
        self.opt_state = ZeroState(
            spec=self._spec,
            held=held,
            step=int(meta["step"]),
            balance_key=None,  # force a re-balance under the new quorum
            ever_balanced=self.opt_state.ever_balanced,
        )
        self._heal_count += 1

    # ------------------------------------------------------------------
    # ownership / re-balance
    # ------------------------------------------------------------------

    def _participation(self) -> Tuple[int, int, Optional[int], int]:
        """(pg_rank, pg_world, participating_rank, num_participants) for
        the current quorum (None participating rank = healing/spare)."""
        manager = self.manager
        pg = manager._pg
        return (
            pg.rank(),
            max(1, pg.size()),
            manager.participating_rank() if manager.is_participating() else None,
            max(1, manager.num_participants()),
        )

    def _owned_shards(self) -> List[int]:
        _pg_rank, _pg_world, my_prank, nparts = self._participation()
        if my_prank is None:
            return []
        owners = shard_assignment(
            self._num_shards, nparts, self.manager.current_step()
        )
        return [s for s in range(self._num_shards) if owners[s] == my_prank]

    def _bootstrap_shard(self, shard: int, flat_params: Any) -> _ShardState:
        import jax.numpy as jnp

        start, _stop = self._spec.shard_range(shard)
        master = jax.lax.dynamic_slice_in_dim(
            flat_params, start, self._spec.shard_len
        )
        return _ShardState(
            step=self.opt_state.step,
            master=master,
            opt=self.tx.init(jnp.zeros((self._spec.shard_len,), jnp.float32)),
        )

    def _maybe_rebalance(self) -> None:
        """Re-balances shard ownership when the quorum's shape changed
        since the last step. Runs on the train-loop thread, in wire
        lockstep with every other PG member (all ranks observe the same
        quorum and reach this seam at the same step). Exchanges only the
        shard states whose ownership moved; lost shards (dead holder)
        reconstruct deterministically."""
        state: ZeroState = self.opt_state
        pg_rank, pg_world, my_prank, nparts = self._participation()
        key = (
            self.manager._quorum_id,
            pg_rank,
            pg_world,
            my_prank,
            nparts,
        )
        if state.balance_key == key:
            return
        owners = shard_assignment(
            self._num_shards, nparts, self.manager.current_step()
        )
        owned = (
            [s for s in range(self._num_shards) if owners[s] == my_prank]
            if my_prank is not None
            else []
        )
        labels = _replica_labels(self.manager)
        if pg_world <= 1:
            # Alone on the wire: no exchange partner. Keep fresh held
            # shards, bootstrap the rest from the replicated params.
            with _trace_of(self.manager).span(
                "zero_rebalance", owned=len(owned), wire=False
            ):
                self._adopt_rebalanced(
                    state, owned, {}, key, labels, ranks_identical=True
                )
            return
        try:
            with _trace_of(self.manager).span(
                "zero_rebalance", owned=len(owned), wire=True
            ):
                self._rebalance_over_wire(
                    state, owners, owned, pg_rank, key, labels
                )
        except Exception as e:  # noqa: BLE001 — poison the step, never raise
            # Comm-layer errors funnel into report_error: the step will
            # not commit and the next quorum reconfigures the wire; the
            # pre-balance state stays live (balance_key unchanged, so the
            # next healthy step retries the exchange).
            logger.exception("ZeRO re-balance failed: %s", e)
            self.manager.report_error(
                e if isinstance(e, Exception) else RuntimeError(str(e))
            )

    def _rebalance_over_wire(
        self,
        state: ZeroState,
        owners: np.ndarray,
        owned: List[int],
        pg_rank: int,
        key: Tuple,
        labels: Dict[str, Any],
    ) -> None:
        pg = self.manager._pg
        _pg_rank, _pg_world, my_prank, _nparts = self._participation()
        # Manifest: [pg_rank, participating_rank(-1), state_step,
        # (shard_id, shard_step) * held]. Tiny — the whole exchange is a
        # few int64s per member.
        entries = sorted(state.held.items())
        manifest = np.array(
            [pg_rank, -1 if my_prank is None else my_prank, state.step]
            + [v for s, sh in entries for v in (s, sh.step)],
            dtype=np.int64,
        )
        gathered = pg.allgather([manifest]).wait()
        manifests: List[Tuple[int, int, Sequence[Tuple[int, int]]]] = []
        participant_pg_ranks: Dict[int, int] = {}
        current_step = state.step
        for arrays in gathered:
            row = np.asarray(arrays[0], dtype=np.int64)
            member_pg, member_prank, member_step = (
                int(row[0]),
                int(row[1]),
                int(row[2]),
            )
            current_step = max(current_step, member_step)
            if member_prank >= 0:
                participant_pg_ranks[member_prank] = member_pg
            pairs = [
                (int(row[i]), int(row[i + 1])) for i in range(3, len(row), 2)
            ]
            manifests.append((member_pg, member_step, pairs))
        moves, _lost = plan_shard_moves(
            manifests, owners, participant_pg_ranks, current_step
        )
        nparts = self._participation()[3]
        ranks_identical = len(participant_pg_ranks) == nparts and all(
            prank == pgr for prank, pgr in participant_pg_ranks.items()
        )
        # Deterministic global order (sorted by shard id) so every rank
        # submits its role ops in the same sequence — the same pairwise
        # progress argument the alltoall ordering makes.
        moved_in: Dict[int, _ShardState] = {}
        for shard, src, dst in moves:
            if src == pg_rank:
                held = state.held[shard]
                arrays = [np.asarray(held.master)] + [
                    np.asarray(leaf)
                    for leaf in jax.tree_util.tree_leaves(held.opt)
                ]
                pg.send(arrays, dst=dst, tag=shard).wait()
            elif dst == pg_rank:
                templates = [
                    np.zeros((self._spec.shard_len,), np.float32)
                ] + [np.array(t) for t in self._opt_leaf_templates]
                received = pg.recv(templates, src=src, tag=shard).wait()
                import jax.numpy as jnp

                moved_in[shard] = _ShardState(
                    step=current_step,
                    master=jnp.asarray(received[0]),
                    opt=jax.tree_util.tree_unflatten(
                        self._opt_treedef,
                        [jnp.asarray(a) for a in received[1:]],
                    ),
                )
                nbytes = sum(int(a.nbytes) for a in received)
                metrics.inc("tpuft_zero_shards_moved_total", **labels)
                metrics.inc("tpuft_zero_rebalance_bytes_total", nbytes, **labels)
        self._adopt_rebalanced(
            state, owned, moved_in, key, labels, ranks_identical=ranks_identical
        )

    def _adopt_rebalanced(
        self,
        state: ZeroState,
        owned: List[int],
        moved_in: Dict[int, _ShardState],
        key: Tuple,
        labels: Dict[str, Any],
        ranks_identical: bool,
    ) -> None:
        flat_params = None
        held: Dict[int, _ShardState] = {}
        for s in owned:
            if s in moved_in:
                held[s] = moved_in[s]
            elif s in state.held and state.held[s].step >= state.step:
                held[s] = state.held[s]
            else:
                if flat_params is None:
                    flat_params = self._spec.pack(self.params)
                held[s] = self._bootstrap_shard(s, flat_params)
                if state.ever_balanced:
                    # The shard was live somewhere before this membership
                    # change and its holder died with it: masters re-pack
                    # exactly from the replicated committed params;
                    # moments restart (the bounded-staleness envelope
                    # docs/zero.md documents).
                    metrics.inc("tpuft_zero_shard_reinits_total", **labels)
                else:
                    metrics.inc("tpuft_zero_shard_bootstraps_total", **labels)
        self.manager.disallow_state_dict_read()
        try:
            self.opt_state = replace(
                state,
                held=held,
                balance_key=key,
                ever_balanced=True,
                ranks_identical=ranks_identical,
            )
        finally:
            self.manager.allow_state_dict_read()
        metrics.inc("tpuft_zero_rebalance_total", **labels)
        metrics.set_gauge("tpuft_zero_owned_shards", len(held), **labels)

    # ------------------------------------------------------------------
    # the sharded step
    # ------------------------------------------------------------------

    def _reduce_grad_shards(
        self, grads: Any, pre_state: ZeroState
    ) -> Optional[Dict[int, np.ndarray]]:
        """Packs ``grads`` into the flat f32 plane and reduces it across
        participating replicas (SUM / live participant count — world-size
        independent; non-participants contribute zeros). Returns the
        averaged ranges for the shards this replica holds (what the
        update consumes), or None when the wire errored (the step is
        already poisoned and will not commit).

        Takes ``pg.reduce_scatter`` — each rank receives ONLY its owned
        block — when the layout provably permits: every PG member is a
        participant sitting at its participant rank (manifest-proven at
        the last re-balance), the block policy gives every rank the same
        number of contiguous shards, and this replica's held set is
        exactly that block. Anything else (healing members in the PG,
        unequal blocks, strided policy) falls back to allreduce + local
        slice — bitwise-identical bytes on the TCP backend, and still one
        collective."""
        manager = self.manager
        spec = self._spec
        flat = np.asarray(spec.pack(grads), dtype=np.float32)
        ids = sorted(pre_state.held)
        if manager.is_lone_replica():
            return {s: spec.shard_view(flat, s) for s in ids}
        nparts = max(1, manager.num_participants())
        if not manager.is_participating():
            flat = np.zeros_like(flat)
        pg = manager._pg
        metrics.inc(
            "tpuft_zero_reduce_scatter_bytes_total", flat.nbytes,
            **_replica_labels(manager),
        )
        # Quantized shard wire ($TPUFT_ZERO_CODEC, fleet-wide like
        # TPUFT_WIRE_DTYPE): the flat f32 grad plane rides the fused
        # dequant-reduce-requant allreduce at ~1/4 (fp8/int8) or ~1/8
        # (int4) of the f32 bytes. Reduced values feed only the OWNED
        # shards' updates, so cross-replica bitwise identity of the
        # reduction is not required here — it is re-established by the
        # allgather leg, where every replica dequantizes the same
        # encoded master payload.
        codec = wire_codec.zero_codec()
        if codec != "fp32":
            n_blocks = -(-flat.size // q.BLOCK)
            pad_blocks = (-n_blocks) % max(pg.size(), 1)
            post = (n_blocks + pad_blocks) * (4 + q.payload_cols(codec)) + (
                q.WIRE_HEADER_BYTES * pg.size()
            )
            metrics.inc(
                "tpuft_codec_bytes_pre_total", flat.nbytes,
                wire="zero", codec=codec,
            )
            metrics.inc(
                "tpuft_codec_bytes_post_total", int(post),
                wire="zero", codec=codec,
            )
            metrics.set_gauge(
                "tpuft_codec_wire", wire_codec.CODEC_GAUGE_CODES[codec],
                wire="zero",
            )
            tracing.record(
                "codec_wire",
                step=manager.current_step(),
                wire="zero",
                codec=codec,
                pre_bytes=int(flat.nbytes),
                post_bytes=int(post),
            )
            try:
                reduced = np.asarray(
                    allreduce_quantized([flat], ReduceOp.SUM, pg, wire_dtype=codec)
                    .wait()[0]
                )
                reduced = (reduced / nparts).astype(np.float32)
                return {s: spec.shard_view(reduced, s) for s in ids}
            except Exception as e:  # noqa: BLE001 — poison, never raise
                logger.exception("ZeRO quantized grad reduce failed: %s", e)
                manager.report_error(
                    e if isinstance(e, Exception) else RuntimeError(str(e))
                )
                return None
        # Every rank derives the branch from globally-agreed facts (PG
        # size vs participant count, shard divisibility, the proven rank
        # identity from the shared manifest round, the shared codec env)
        # so no rank can enter reduce_scatter while a peer enters
        # allreduce.
        fast = (
            pre_state.ranks_identical
            and pg.size() == nparts
            and self._num_shards % nparts == 0
            and os.environ.get(ENV_ZERO_REBALANCE, "block") == "block"
        )
        try:
            if fast:
                block = self._num_shards // nparts
                work = pg.reduce_scatter(
                    [flat.reshape(nparts, block * spec.shard_len)],
                    ReduceOp.SUM,
                )
                mine = np.asarray(work.wait()[0]).reshape(-1)
                mine = (mine / nparts).astype(np.float32)
                my_prank = manager.participating_rank()
                first = (my_prank or 0) * block
                out: Dict[int, np.ndarray] = {}
                for slot in range(block):
                    shard = first + slot
                    if shard in pre_state.held:
                        out[shard] = mine[
                            slot * spec.shard_len : (slot + 1) * spec.shard_len
                        ]
                return out
            reduced = np.asarray(pg.allreduce([flat], ReduceOp.SUM).wait()[0])
            reduced = (reduced / nparts).astype(np.float32)
            return {s: spec.shard_view(reduced, s) for s in ids}
        except Exception as e:  # noqa: BLE001 — poison, never raise
            logger.exception("ZeRO grad reduce failed: %s", e)
            manager.report_error(
                e if isinstance(e, Exception) else RuntimeError(str(e))
            )
            return None

    def _allgather_masters(
        self, updated: Dict[int, Any]
    ) -> Optional[np.ndarray]:
        """Allgathers the owned updated master ranges; returns the full
        new flat f32 buffer (identical bytes on every replica), or None on
        a wire error. Ranges no live owner covered — only possible
        transiently while a quorum settles — keep their previous values
        (lazily re-packed from the current params; the healthy path never
        pays that extra device fetch)."""
        manager = self.manager
        pg = manager._pg
        spec = self._spec
        ids = sorted(updated)
        # Quantized shard wire: owners encode their updated master ranges
        # and EVERY replica — owners included — dequantizes the same
        # encoded allgather payload through the same deterministic host
        # codec, so params stay bitwise identical across replicas BY
        # CONSTRUCTION (the wire bytes, not each owner's f32 local copy,
        # are the source of truth for params). Masters themselves stay
        # f32 on their owners; only the wire narrows.
        codec = wire_codec.zero_codec()
        shard_blocks = -(-spec.shard_len // q.BLOCK)
        if codec == "fp32":
            payload = [np.array(ids, dtype=np.int64)] + [
                np.asarray(updated[s], dtype=np.float32) for s in ids
            ]
        else:
            payload = [np.array(ids, dtype=np.int64)]
            pre = 0
            for s in ids:
                rng = np.asarray(updated[s], dtype=np.float32)
                pre += rng.nbytes
                payload.append(q.pack_arrays(*q.quantize_blocks(rng, wire=codec)))
            post = sum(int(a.nbytes) for a in payload[1:])
            metrics.inc(
                "tpuft_codec_bytes_pre_total", pre, wire="zero", codec=codec
            )
            metrics.inc(
                "tpuft_codec_bytes_post_total", post, wire="zero", codec=codec
            )
        sent = sum(int(a.nbytes) for a in payload[1:])
        metrics.inc(
            "tpuft_zero_allgather_bytes_total", sent,
            **_replica_labels(manager),
        )
        if manager.is_lone_replica():
            gathered = [payload]
        else:
            try:
                gathered = pg.allgather(payload).wait()
            except Exception as e:  # noqa: BLE001 — poison, never raise
                logger.exception("ZeRO param allgather failed: %s", e)
                manager.report_error(
                    e if isinstance(e, Exception) else RuntimeError(str(e))
                )
                return None
        flat = np.empty(spec.padded, dtype=np.float32)
        covered = np.zeros(spec.num_shards, dtype=bool)
        for arrays in gathered:
            row_ids = np.asarray(arrays[0], dtype=np.int64)
            for slot, shard in enumerate(row_ids):
                start, stop = spec.shard_range(int(shard))
                if codec == "fp32":
                    rng = np.asarray(arrays[1 + slot], np.float32)
                else:
                    # unpack_arrays' embedded format tag asserts the
                    # sender used OUR codec — a cross-rank
                    # TPUFT_ZERO_CODEC disagreement is a hard error,
                    # never a silent misdecode.
                    p, sc = q.unpack_arrays(
                        np.asarray(arrays[1 + slot], np.uint8).reshape(-1),
                        shard_blocks,
                        wire=codec,
                    )
                    rng = q.dequantize_blocks(
                        p, sc, (spec.shard_len,), np.float32
                    )
                flat[start:stop] = rng
                covered[int(shard)] = True
        if not covered.all():
            fallback = np.asarray(spec.pack(self.params), dtype=np.float32)
            for shard in np.flatnonzero(~covered):
                start, stop = spec.shard_range(int(shard))
                flat[start:stop] = fallback[start:stop]
        return flat

    def _zero_speculate(
        self, avg_blocks: Optional[Dict[int, np.ndarray]], pre_state: ZeroState
    ) -> Tuple[Any, Any]:
        """The sharded update + param allgather from the averaged
        gradient ranges of the held shards; returns ``(speculation,
        recompute)`` with the base-class contract (recompute re-derives
        against a state the commit barrier healed)."""
        import jax.numpy as jnp

        spec = self._spec
        if avg_blocks is None:
            # Wire already errored: the commit will fail and the
            # speculation is discarded; hand back the pre-step state so
            # the machinery has something well-formed to (not) adopt.
            return (self.params, pre_state), lambda: (self.params, self.opt_state)

        ids = sorted(avg_blocks)
        new_held: Dict[int, _ShardState] = dict(pre_state.held)
        updated_masters: Dict[int, Any] = {}
        if ids:
            with metrics.timer("tpuft_update_dispatch_seconds"):
                new_masters, new_opts = self._jit_shard_update(
                    [jnp.asarray(avg_blocks[s]) for s in ids],
                    [pre_state.held[s].opt for s in ids],
                    [pre_state.held[s].master for s in ids],
                )
            for slot, s in enumerate(ids):
                new_held[s] = _ShardState(
                    step=pre_state.step + 1,
                    master=new_masters[slot],
                    opt=new_opts[slot],
                )
                updated_masters[s] = new_masters[slot]
        new_flat = self._allgather_masters(updated_masters)
        if new_flat is None:
            return (self.params, pre_state), lambda: (self.params, self.opt_state)
        new_params = spec.unpack(jnp.asarray(new_flat))
        spec_state = replace(pre_state, held=new_held, step=pre_state.step + 1)

        def recompute() -> Tuple[Any, Any]:
            # The barrier healed this replica mid-step: the allgathered
            # flat buffer is the committed truth for params (owners
            # computed it from the same averaged gradients), and the
            # healed state supplies shard states for anything the heal
            # restored; my own owned shards keep the updates computed
            # above (derived from the pre-heal committed state — the
            # load_state_dict + optimizer.step() order).
            healed: ZeroState = self.opt_state
            merged = dict(healed.held)
            for s, sh in new_held.items():
                if s in updated_masters or s not in merged:
                    merged[s] = sh
            return (
                spec.unpack(jnp.asarray(new_flat)),
                replace(healed, held=merged, step=healed.step + 1,
                        balance_key=None),
            )

        return (new_params, spec_state), recompute

    # -- Optimizer seams ----------------------------------------------

    def _wire_speculate(self, grads: Any, pre_opt: Any, pre_params: Any,
                        should_quantize: bool):
        if should_quantize:
            _warn_quantize_once()
        self._maybe_rebalance()
        pre_state: ZeroState = self.opt_state  # re-read: rebalance rebinds
        avg_blocks = self._reduce_grad_shards(grads, pre_state)
        return self._zero_speculate(avg_blocks, pre_state)

    def _wire_step(self, grad_fn: Any, batch: Any, should_quantize: bool):
        if should_quantize:
            _warn_quantize_once()
        loss, grads = grad_fn(self.params, *batch)
        committed = self.step(grads)
        return loss, committed

    def _lone_dispatch(self, fused: Any, grad_fn: Any, batch: Any):
        self._maybe_rebalance()
        pre_params = self.params
        pre_state: ZeroState = self.opt_state
        with metrics.timer("tpuft_update_dispatch_seconds"):
            loss, grads = grad_fn(pre_params, *batch)
        avg_blocks = self._reduce_grad_shards(grads, pre_state)
        spec, recompute = self._zero_speculate(avg_blocks, pre_state)
        return loss, spec, recompute

    def step(self, grads: Any, timeout: Optional[float] = None) -> bool:
        """Commits one sharded step from the **local** gradient pytree
        (contrast :meth:`Optimizer.step`, which takes pre-averaged
        gradients): reduce-scatter, shard update, param allgather, then
        the commit barrier. The collectives complete before the vote
        launches — a rank whose sync failed must not vote commit."""
        grads = _sync_device(grads)
        heal_count = self._heal_count
        self._maybe_rebalance()
        pre_state: ZeroState = self.opt_state
        avg_blocks = self._reduce_grad_shards(grads, pre_state)
        spec, recompute = self._zero_speculate(avg_blocks, pre_state)
        return self._commit_and_adopt(heal_count, spec, recompute, timeout)


_WARNED_QUANTIZE = [False]


def _warn_quantize_once() -> None:
    if not _WARNED_QUANTIZE[0]:
        _WARNED_QUANTIZE[0] = True
        logger.warning(
            "should_quantize is a no-op on the ZeRO sharded wire; set "
            "TPUFT_ZERO_CODEC=fp8|int8|int4 instead (the codec is a wire "
            "format every replica must agree on, not a per-step flag — "
            "see docs/zero.md)"
        )
